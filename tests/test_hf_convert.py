"""HF checkpoint conversion: logit parity vs transformers' Llama.

The production path is `LlamaRuntime.from_hf(dir)` on any local HF Llama
checkpoint (the capability replacing the reference's Ollama hop,
reference: services/dashboard/app.py:1182-1258). Zero-egress image means no
real pretrained weights on disk, so these tests build genuine
``transformers.LlamaForCausalLM`` checkpoints (random weights, exact
architecture + serialization format) and require our forward to reproduce
HF's logits bit-closely — the same evidence a TinyLlama download would give,
minus the download.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kakveda_tpu.models.generate import LlamaRuntime, generate_tokens
from kakveda_tpu.models.hf_convert import hf_config_to_llama, load_hf_checkpoint
from kakveda_tpu.models.llama import forward

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


def _make_hf_checkpoint(path, *, vocab=256, tie=False, rope_scaling=None, seed=0):
    hf_cfg = transformers.LlamaConfig(
        vocab_size=vocab,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        tie_word_embeddings=tie,
        rope_scaling=rope_scaling,
    )
    torch.manual_seed(seed)
    model = transformers.LlamaForCausalLM(hf_cfg).eval()
    model.save_pretrained(str(path), safe_serialization=True)
    return model


def _hf_logits(model, ids: np.ndarray) -> np.ndarray:
    with torch.no_grad():
        return model(torch.from_numpy(ids)).logits.float().numpy()


def _assert_parity(model, path, *, vocab):
    params, cfg = load_hf_checkpoint(str(path), param_dtype=jnp.float32)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, size=(2, 17), dtype=np.int64)
    ours = np.asarray(forward(params, cfg, jnp.asarray(ids)))[:, :, :vocab]
    theirs = _hf_logits(model, ids)
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)
    return params, cfg


def test_logit_parity_untied(tmp_path):
    model = _make_hf_checkpoint(tmp_path, vocab=256)
    _assert_parity(model, tmp_path, vocab=256)


def test_logit_parity_tied_embeddings(tmp_path):
    model = _make_hf_checkpoint(tmp_path, vocab=256, tie=True, seed=1)
    _assert_parity(model, tmp_path, vocab=256)


def test_logit_parity_llama3_rope_scaling(tmp_path):
    scaling = {
        "rope_type": "llama3",
        "factor": 8.0,
        "low_freq_factor": 1.0,
        "high_freq_factor": 4.0,
        "original_max_position_embeddings": 64,
    }
    model = _make_hf_checkpoint(tmp_path, vocab=256, rope_scaling=scaling, seed=2)
    params, cfg = _assert_parity(model, tmp_path, vocab=256)
    assert cfg.rope_factor == 8.0


def test_vocab_padding_masks_sampling(tmp_path):
    # 250 is not a multiple of 8: the table pads to 256 and sampling must
    # never emit ids 250-255 (their embed rows are zeros, logits could win).
    model = _make_hf_checkpoint(tmp_path, vocab=250, seed=3)
    params, cfg = load_hf_checkpoint(str(tmp_path), param_dtype=jnp.float32)
    assert cfg.vocab_size == 256 and cfg.effective_vocab == 250

    ids = np.random.default_rng(1).integers(0, 250, size=(1, 9), dtype=np.int64)
    ours = np.asarray(forward(params, cfg, jnp.asarray(ids)))[:, :, :250]
    np.testing.assert_allclose(ours, _hf_logits(model, ids), rtol=2e-4, atol=2e-4)

    out = generate_tokens(params, cfg, list(ids[0]), max_new_tokens=24, temperature=0.8)
    assert out and all(t < 250 for t in out)


def test_decode_cache_matches_full_forward(tmp_path, decode_parity):
    # The serving path (KV-cache decode) must agree with the parity-tested
    # full forward on a converted checkpoint, not just on random init.
    _make_hf_checkpoint(tmp_path, vocab=256, seed=4)
    params, cfg = load_hf_checkpoint(str(tmp_path), param_dtype=jnp.float32)
    decode_parity(params, cfg, list(range(5, 20)), n=8)


def _write_tokenizer(path, *, vocab_target=256):
    """Train a tiny real BPE tokenizer in-process and save HF tokenizer files
    alongside the checkpoint — the same on-disk layout a downloaded
    checkpoint directory has."""
    from tokenizers import Tokenizer, models, pre_tokenizers, trainers

    tok = Tokenizer(models.BPE(unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    trainer = trainers.BpeTrainer(
        vocab_size=vocab_target, special_tokens=["<unk>", "<s>", "</s>"]
    )
    corpus = [
        "summarize the article with citations",
        "explain the theory with references",
        "the quick brown fox jumps over the lazy dog",
        "failure intelligence for language model applications",
    ] * 8
    tok.train_from_iterator(corpus, trainer)
    fast = transformers.PreTrainedTokenizerFast(
        tokenizer_object=tok, bos_token="<s>", eos_token="</s>", unk_token="<unk>"
    )
    fast.save_pretrained(str(path))
    return fast


def test_runtime_from_hf_end_to_end(tmp_path):
    _make_hf_checkpoint(tmp_path, vocab=256, seed=5)
    _write_tokenizer(tmp_path)
    rt = LlamaRuntime.from_hf(str(tmp_path))
    assert rt.tokenizer.vocab_size <= rt.cfg.vocab_size
    res = rt.generate("summarize the article", max_tokens=8)
    assert isinstance(res.text, str)
    assert res.meta["provider"] == "tpu"
    assert res.meta["model"] == tmp_path.name
    batch = rt.generate_batch(["explain the theory", "quick brown fox"], max_tokens=4)
    assert len(batch) == 2


def _make_qwen2_checkpoint(path, *, vocab=256, seed=0):
    hf_cfg = transformers.Qwen2Config(
        vocab_size=vocab,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    torch.manual_seed(seed)
    model = transformers.Qwen2ForCausalLM(hf_cfg).eval()
    # transformers zero-inits Linear biases; randomize them so parity
    # genuinely exercises the bias path.
    with torch.no_grad():
        for lyr in model.model.layers:
            for proj in (lyr.self_attn.q_proj, lyr.self_attn.k_proj, lyr.self_attn.v_proj):
                proj.bias.normal_(0.0, 0.5)
    model.save_pretrained(str(path), safe_serialization=True)
    return model


def _make_mistral_checkpoint(path, *, vocab=256, sliding_window=None, seed=0):
    hf_cfg = transformers.MistralConfig(
        vocab_size=vocab,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        sliding_window=sliding_window,
        tie_word_embeddings=False,
    )
    torch.manual_seed(seed)
    model = transformers.MistralForCausalLM(hf_cfg).eval()
    model.save_pretrained(str(path), safe_serialization=True)
    return model


def test_logit_parity_qwen2_attention_bias(tmp_path):
    # Qwen2 hardcodes q/k/v projection biases (no config flag) — random-init
    # HF biases are nonzero, so parity here proves the bias path end to end.
    model = _make_qwen2_checkpoint(tmp_path, seed=6)
    params, cfg = _assert_parity(model, tmp_path, vocab=256)
    assert cfg.attn_bias
    assert float(np.abs(np.asarray(params["layers"][0]["bq"])).sum()) > 0


def test_logit_parity_mistral_sliding_window(tmp_path):
    # window=8 over a 17-token sequence: positions past the window genuinely
    # change the mask, so parity proves the sliding-window semantics match
    # HF's (keep iff q_pos − k_pos < window).
    model = _make_mistral_checkpoint(tmp_path, sliding_window=8, seed=7)
    params, cfg = _assert_parity(model, tmp_path, vocab=256)
    assert cfg.sliding_window == 8

    # And the windowed mask must differ from full causal — guard against a
    # silently ignored window (parity would still pass if HF ignored it too).
    import dataclasses

    full = dataclasses.replace(cfg, sliding_window=0)
    ids = np.random.default_rng(3).integers(0, 256, size=(1, 17), dtype=np.int64)
    ours_win = np.asarray(forward(params, cfg, jnp.asarray(ids)))
    ours_full = np.asarray(forward(params, full, jnp.asarray(ids)))
    assert np.abs(ours_win - ours_full).max() > 1e-3


def test_mistral_decode_cache_matches_full_forward(tmp_path, decode_parity):
    # The cached decode path applies the window in slot space (offsets
    # cancel); greedy parity with the parity-tested full forward proves it.
    _make_mistral_checkpoint(tmp_path, sliding_window=8, seed=8)
    params, cfg = load_hf_checkpoint(str(tmp_path), param_dtype=jnp.float32)
    decode_parity(params, cfg, list(range(5, 25)), n=8)


def test_qwen2_decode_cache_matches_full_forward(tmp_path, decode_parity):
    _make_qwen2_checkpoint(tmp_path, seed=9)
    params, cfg = load_hf_checkpoint(str(tmp_path), param_dtype=jnp.float32)
    decode_parity(params, cfg, list(range(3, 17)), n=8)


def _make_mixtral_checkpoint(path, *, vocab=256, seed=0):
    hf_cfg = transformers.MixtralConfig(
        vocab_size=vocab,
        hidden_size=64,
        intermediate_size=96,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_local_experts=4,
        num_experts_per_tok=2,
        max_position_embeddings=128,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        sliding_window=None,
        tie_word_embeddings=False,
    )
    torch.manual_seed(seed)
    model = transformers.MixtralForCausalLM(hf_cfg).eval()
    model.save_pretrained(str(path), safe_serialization=True)
    return model


def test_logit_parity_mixtral_moe(tmp_path):
    # Sparse-MoE checkpoint: the converter stacks per-expert w1/w2/w3 into
    # [E, ...] arrays and the runtime's dispatch/combine must reproduce
    # HF's token-choice routing exactly (no capacity drops at this scale).
    model = _make_mixtral_checkpoint(tmp_path, seed=10)
    params, cfg = _assert_parity(model, tmp_path, vocab=256)
    assert cfg.n_experts == 4 and cfg.n_experts_per_tok == 2
    assert params["layers"][0]["we_gate"].shape == (4, 64, 96)


def test_mixtral_runtime_serving_end_to_end(tmp_path):
    _make_mixtral_checkpoint(tmp_path, seed=11)
    _write_tokenizer(tmp_path)
    rt = LlamaRuntime.from_hf(str(tmp_path))
    res = rt.generate("summarize the article", max_tokens=8)
    assert isinstance(res.text, str) and res.meta["provider"] == "tpu"
    # deterministic greedy serving
    assert rt.generate("summarize the article", max_tokens=8).text == res.text


def _make_gemma_checkpoint(path, *, vocab=256, seed=0):
    hf_cfg = transformers.GemmaConfig(
        vocab_size=vocab,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=32,  # != hidden/heads (16) — gemma-7b-style explicit head_dim
        max_position_embeddings=128,
        rms_norm_eps=1e-6,
        rope_theta=10000.0,
    )
    torch.manual_seed(seed)
    model = transformers.GemmaForCausalLM(hf_cfg).eval()
    # zero-init (1+w) norms hide conversion bugs; randomize them
    with torch.no_grad():
        for lyr in model.model.layers:
            lyr.input_layernorm.weight.normal_(0.0, 0.2)
            lyr.post_attention_layernorm.weight.normal_(0.0, 0.2)
        model.model.norm.weight.normal_(0.0, 0.2)
    model.save_pretrained(str(path), safe_serialization=True)
    return model


def test_logit_parity_gemma(tmp_path):
    # Gemma: GeGLU gate, sqrt(d_model) embedding scale, (1+w) norms
    # (materialized at conversion), explicit head_dim != d_model/heads,
    # tied embeddings.
    model = _make_gemma_checkpoint(tmp_path, seed=12)
    params, cfg = _assert_parity(model, tmp_path, vocab=256)
    assert cfg.act_fn == "gelu_tanh" and cfg.scale_embed
    assert cfg.head_dim == 32
    # norms carry the +1 offset: random N(0, 0.2) weights center near 1
    m = float(np.mean(np.asarray(params["final_norm"])))
    assert 0.7 < m < 1.3, m

    # At bf16 param_dtype the materialized 1+w gains must stay f32 (bf16
    # spacing near 1.0 is 2^-8 — it would swamp the zero-centered
    # parameterization); non-gemma norms follow param_dtype as before.
    bf_params, _ = load_hf_checkpoint(str(tmp_path), param_dtype=jnp.bfloat16)
    assert bf_params["final_norm"].dtype == jnp.float32
    assert bf_params["layers"][0]["attn_norm"].dtype == jnp.float32
    assert bf_params["layers"][0]["wq"].dtype == jnp.bfloat16


def test_gemma_decode_cache_matches_full_forward(tmp_path, decode_parity):
    _make_gemma_checkpoint(tmp_path, seed=13)
    params, cfg = load_hf_checkpoint(str(tmp_path), param_dtype=jnp.float32)
    decode_parity(params, cfg, list(range(5, 21)), n=8)


def _make_gemma2_checkpoint(path, *, vocab=256, seed=0, sliding_window=8):
    hf_cfg = transformers.Gemma2Config(
        vocab_size=vocab,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=4,  # even+odd layers: alternation must matter
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=32,
        max_position_embeddings=128,
        rms_norm_eps=1e-6,
        rope_theta=10000.0,
        sliding_window=sliding_window,
        attn_logit_softcapping=20.0,
        final_logit_softcapping=10.0,
        query_pre_attn_scalar=64,  # != head_dim (32) → explicit query scale
    )
    torch.manual_seed(seed)
    model = transformers.Gemma2ForCausalLM(hf_cfg).eval()
    with torch.no_grad():
        for lyr in model.model.layers:
            for nm in (
                lyr.input_layernorm,
                lyr.post_attention_layernorm,
                lyr.pre_feedforward_layernorm,
                lyr.post_feedforward_layernorm,
            ):
                nm.weight.normal_(0.0, 0.2)
        model.model.norm.weight.normal_(0.0, 0.2)
    model.save_pretrained(str(path), safe_serialization=True)
    return model


def test_logit_parity_gemma2(tmp_path):
    # Gemma-2: alternating sliding windows over a 17-token sequence
    # (window 8 < seq, so even/odd layers genuinely mask differently),
    # attention + final softcapping, query_pre_attn_scalar != head_dim,
    # sandwich post-norms.
    model = _make_gemma2_checkpoint(tmp_path, seed=14)
    params, cfg = _assert_parity(model, tmp_path, vocab=256)
    assert cfg.alt_window and cfg.sliding_window == 8
    assert cfg.attn_softcap == 20.0 and cfg.final_softcap == 10.0
    assert cfg.post_norms and abs(cfg.query_scale - 64**-0.5) < 1e-12
    assert "post_attn_norm" in params["layers"][0]


def test_gemma2_decode_cache_matches_full_forward(tmp_path, decode_parity):
    _make_gemma2_checkpoint(tmp_path, seed=15)
    params, cfg = load_hf_checkpoint(str(tmp_path), param_dtype=jnp.float32)
    # prompt long enough that the window alternation bites
    decode_parity(params, cfg, list(range(5, 25)), n=8)


def test_logit_parity_qwen3_qk_norm(tmp_path, decode_parity):
    # Qwen3: per-head q/k RMSNorm over head_dim (pre-RoPE), no qkv bias,
    # explicit head_dim.
    hf_cfg = transformers.Qwen3Config(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=32,
        max_position_embeddings=128,
        rms_norm_eps=1e-6,
        rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    torch.manual_seed(17)
    model = transformers.Qwen3ForCausalLM(hf_cfg).eval()
    with torch.no_grad():  # randomize the qk norms (ones-init hides bugs)
        for lyr in model.model.layers:
            lyr.self_attn.q_norm.weight.normal_(1.0, 0.2)
            lyr.self_attn.k_norm.weight.normal_(1.0, 0.2)
    model.save_pretrained(str(tmp_path), safe_serialization=True)
    params, cfg = _assert_parity(model, tmp_path, vocab=256)
    assert cfg.qk_norm and not cfg.attn_bias and cfg.head_dim == 32
    assert params["layers"][0]["q_norm"].shape == (32,)

    # cached decode inherits the qk-norm path
    decode_parity(params, cfg, list(range(5, 19)), n=6)


def test_gemma2_continuous_batcher_matches_solo(tmp_path):
    """The continuous batcher's per-slot validity masks must implement the
    alternating window + softcaps + sandwich norms identically to the
    plain cached decode."""
    from kakveda_tpu.models.serving import ContinuousBatcher

    _make_gemma2_checkpoint(tmp_path, seed=16)
    params, cfg = load_hf_checkpoint(str(tmp_path), param_dtype=jnp.float32)
    prompts = [list(range(4, 18)), list(range(30, 39)), list(range(50, 70))]
    cb = ContinuousBatcher(params, cfg, batch_slots=3, max_len=96)
    cont = cb.run_all(prompts, max_new_tokens=8)
    solo = [generate_tokens(params, cfg, p, max_new_tokens=8) for p in prompts]
    assert cont == solo


def _make_phi3_checkpoint(path, *, vocab=256, seed=0, long_context=False):
    rope = None
    if long_context:
        rng = np.random.default_rng(seed)
        rope = {
            "type": "longrope",
            # head_dim/2 = 8 per-dim divisors
            "short_factor": [float(x) for x in rng.uniform(1.0, 1.5, 8)],
            "long_factor": [float(x) for x in rng.uniform(2.0, 6.0, 8)],
        }
    hf_cfg = transformers.Phi3Config(
        vocab_size=vocab,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=256 if long_context else 128,
        original_max_position_embeddings=128,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        rope_scaling=rope,
        sliding_window=None,
        tie_word_embeddings=False,
        pad_token_id=0,  # default 32000 exceeds the tiny vocab
    )
    torch.manual_seed(seed)
    model = transformers.Phi3ForCausalLM(hf_cfg).eval()
    model.save_pretrained(str(path), safe_serialization=True)
    return model


def test_logit_parity_phi3_fused_projections(tmp_path):
    # Phi-3: fused qkv_proj and gate_up_proj split at conversion.
    model = _make_phi3_checkpoint(tmp_path, seed=22)
    params, cfg = _assert_parity(model, tmp_path, vocab=256)
    assert params["layers"][0]["wq"].shape == (64, 64)
    assert params["layers"][0]["w_gate"].shape == (64, 128)


def test_logit_parity_phi3_longrope(tmp_path, decode_parity):
    # longrope with max_position > original: HF switches short → long
    # factors dynamically when the sequence exceeds the original context;
    # attention scaling is static. Parity in BOTH regimes.
    model = _make_phi3_checkpoint(tmp_path, seed=23, long_context=True)
    params, cfg = _assert_parity(model, tmp_path, vocab=256)  # short regime (17 tokens)
    assert len(cfg.rope_dim_factors) == len(cfg.rope_dim_factors_long) == 8
    assert cfg.rope_attn_scaling > 1.0 and cfg.rope_original_max_len == 128

    # long regime: 140 tokens > original_max (128)
    ids = np.random.default_rng(5).integers(0, 256, size=(1, 140), dtype=np.int64)
    ours = np.asarray(forward(params, cfg, jnp.asarray(ids)))[:, :, :256]
    np.testing.assert_allclose(ours, _hf_logits(model, ids), rtol=2e-4, atol=2e-4)

    # cached decode inherits the scaled rope
    decode_parity(params, cfg, list(range(5, 19)), n=6)


def test_phi3_longrope_mixed_regime_batch_matches_solo(tmp_path):
    """One slot deep in the long-rope regime must not flip a co-batched
    short sequence's rotations: regime selection is per row, so
    continuous-batched output equals solo output for both."""
    from kakveda_tpu.models.serving import ContinuousBatcher

    _make_phi3_checkpoint(tmp_path, seed=24, long_context=True)
    params, cfg = load_hf_checkpoint(str(tmp_path), param_dtype=jnp.float32)
    rng = np.random.default_rng(2)
    long_p = [int(x) for x in rng.integers(5, 250, 126)]  # crosses 128 while decoding
    short_p = [int(x) for x in rng.integers(5, 250, 12)]
    solo = [generate_tokens(params, cfg, p, max_new_tokens=10) for p in (long_p, short_p)]
    cb = ContinuousBatcher(params, cfg, batch_slots=2, max_len=256)
    cont = cb.run_all([long_p, short_p], max_new_tokens=10)
    assert cont == solo


def test_phi3_longrope_chunked_prefill_matches_single_shot(tmp_path):
    """A >original_max_len prompt prefilled in chunks must rotate EVERY
    chunk's K/V with the long factors — regime selection reads the full
    prompt length (threaded via ``seq_total``), not the chunk's own max
    position, or early chunks land in the short regime and diverge from
    single-shot prefill. Asserted on logits: on a tiny random model the
    regime mismatch shifts the final logits by ~5e-4 — far above runtime
    reorder noise (~1e-6) but not enough to flip a greedy argmax, so a
    token-level comparison would pass even with the bug present."""
    from kakveda_tpu.models.generate import _pack_prompts, prefill
    from kakveda_tpu.models.llama import init_cache

    _make_phi3_checkpoint(tmp_path, seed=25, long_context=True)
    params, cfg = load_hf_checkpoint(str(tmp_path), param_dtype=jnp.float32)
    rng = np.random.default_rng(7)
    long_p = [int(x) for x in rng.integers(5, 250, 140)]  # > original_max (128)
    ml = 256

    def last_logits(chunk, plen):
        toks, valid, offs, _ = _pack_prompts([long_p], ml, plen=plen)
        cache = init_cache(cfg, batch=1, max_len=ml)
        last, _ = prefill(
            params, cfg, jnp.asarray(toks), cache,
            jnp.asarray(valid), jnp.asarray(offs), chunk=chunk,
        )
        return np.asarray(last)[:, :256]

    single = last_logits(0, 140)
    for chunk, plen in ((32, 160), (64, 192)):  # early chunks end < 128
        np.testing.assert_allclose(last_logits(chunk, plen), single, atol=2e-5, rtol=0)


def test_multi_model_runtime_hbm_budget_evicts_then_refuses(tmp_path, monkeypatch):
    """With KAKVEDA_HBM_BUDGET set: a load that would cross the budget
    LRU-evicts idle models first; when even eviction can't make room it
    raises HBMBudgetError BEFORE touching the weights (never OOM). The
    pre-load estimate comes from config.json alone (eval_shape)."""
    from kakveda_tpu.models.runtime import HBMBudgetError, MultiModelRuntime

    d1, d2 = tmp_path / "m-one", tmp_path / "m-two"
    for d, seed in ((d1, 30), (d2, 31)):
        _make_hf_checkpoint(d, vocab=256, seed=seed)
        _write_tokenizer(d)

    monkeypatch.delenv("KAKVEDA_HBM_BUDGET", raising=False)
    mm = MultiModelRuntime([str(d1), str(d2)])
    one_cost = mm._estimate_bytes(str(d1))
    mm._get("m-one")
    exact = mm.loaded_bytes()
    # the estimate is honest: right order of magnitude vs exact accounting
    assert 0.5 * exact <= one_cost <= 2.0 * exact, (one_cost, exact)

    # budget fits ONE model: requesting the second evicts the first
    mm2 = MultiModelRuntime([str(d1), str(d2)], hbm_budget_bytes=int(exact * 1.5))
    rt_one = mm2._get("m-one")
    assert set(mm2._loaded) == {"m-one"}
    mm2._get("m-two")
    assert set(mm2._loaded) == {"m-two"}, "LRU eviction did not run"
    assert mm2.loaded_bytes() <= int(exact * 1.5)
    # the survivor still serves
    assert mm2.generate("hi", model="m-two").text is not None
    # an in-flight holder of the evicted runtime: retired (never rebuilds
    # a KV pool behind the budget's back) but still serves via solo decode
    assert rt_one._retired and rt_one.engine() is None
    assert rt_one.generate("still works", max_tokens=4).text is not None

    # budget fits NOTHING: clear refusal, not an OOM
    mm3 = MultiModelRuntime([str(d1)], hbm_budget_bytes=1024)
    with pytest.raises(HBMBudgetError, match="HBM budget"):
        mm3._get("m-one")
    assert mm3._loaded == {}


def test_multi_model_runtime_routes_by_label(tmp_path, monkeypatch):
    """KAKVEDA_HF_CKPTS serves several checkpoints behind one runtime:
    labels come from dir basenames, loading is lazy, and generation routes
    to the right weights (different checkpoints → different logits)."""
    import os

    from kakveda_tpu.models.runtime import MultiModelRuntime, get_runtime, list_models

    d1 = tmp_path / "llama-tiny"
    d2 = tmp_path / "qwen3-tiny"
    _make_hf_checkpoint(d1, vocab=256, seed=20)
    _write_tokenizer(d1)
    hf_cfg = transformers.Qwen3Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=32, max_position_embeddings=128, tie_word_embeddings=False,
    )
    torch.manual_seed(21)
    transformers.Qwen3ForCausalLM(hf_cfg).eval().save_pretrained(str(d2), safe_serialization=True)
    _write_tokenizer(d2)

    rt = MultiModelRuntime([str(d1), str(d2)])
    assert rt.list_models() == ["llama-tiny", "qwen3-tiny"]
    assert not rt._loaded  # lazy: nothing loaded yet
    r1 = rt.generate("the quick brown fox", model="llama-tiny", max_tokens=6)
    assert set(rt._loaded) == {"llama-tiny"}  # only the requested model
    r2 = rt.generate("the quick brown fox", model="qwen3-tiny", max_tokens=6)
    assert r1.meta["provider"] == r2.meta["provider"] == "tpu"
    # default model = first entry
    rd = rt.generate("the quick brown fox", max_tokens=6)
    assert rd.text == r1.text
    with pytest.raises(ValueError, match="available"):
        rt.generate("x", model="nope")

    # env-driven construction through the registry
    monkeypatch.setenv("KAKVEDA_MODEL_RUNTIME", "tpu")
    monkeypatch.setenv("KAKVEDA_HF_CKPTS", os.pathsep.join([str(d1), str(d2)]))
    from kakveda_tpu.models import runtime as runtime_mod

    monkeypatch.setattr(runtime_mod, "_RUNTIMES", {})
    env_rt = get_runtime()
    assert list_models(env_rt) == ["llama-tiny", "qwen3-tiny"]


def test_rejects_unknown_family_and_unknown_scaling(tmp_path):
    with pytest.raises(ValueError, match="model_type"):
        hf_config_to_llama({"model_type": "gpt2", "vocab_size": 8})
    with pytest.raises(ValueError, match="rope_scaling"):
        hf_config_to_llama(
            {
                "model_type": "llama",
                "vocab_size": 8,
                "hidden_size": 8,
                "num_hidden_layers": 1,
                "num_attention_heads": 1,
                "intermediate_size": 8,
                "rope_scaling": {"rope_type": "yarn", "factor": 2.0},
            }
        )


def test_runtime_from_hf_sharded_serving(tmp_path):
    """Real-weight serving on a mesh: from_hf(..., mesh=) places params per
    the TP layout and generates the same greedy text as unsharded serving."""
    from kakveda_tpu.models.generate import LlamaRuntime
    from kakveda_tpu.models.llama import param_specs
    from kakveda_tpu.parallel.mesh import create_mesh

    _make_hf_checkpoint(tmp_path, vocab=256)
    _write_tokenizer(tmp_path)
    plain = LlamaRuntime.from_hf(str(tmp_path))
    expected = plain.generate("the quick brown", max_tokens=6).text

    mesh = create_mesh("dp:1,tp:2")
    rt = LlamaRuntime.from_hf(str(tmp_path), mesh=mesh)
    wq = rt.params["layers"][0]["wq"]
    assert wq.sharding.spec == param_specs(rt.cfg)["layers"][0]["wq"]
    got = rt.generate("the quick brown", max_tokens=6)
    assert got.text == expected
    assert got.meta["provider"] == "tpu"


def test_engine_pool_bytes_reflects_kv_quant(monkeypatch):
    """Budget accounting charges ~1.06 B/element for int8 KV pools (int8
    values + one f32 per-row scale per head_dim), not the dense dtype's 2 B
    (ADVICE r4: over-charging skews the admin panel and evicts early)."""
    from kakveda_tpu.models.llama import LlamaConfig
    from kakveda_tpu.models.runtime import MultiModelRuntime

    cfg = LlamaConfig()
    monkeypatch.delenv("KAKVEDA_KV_QUANT", raising=False)
    dense = MultiModelRuntime._engine_pool_bytes(cfg)
    monkeypatch.setenv("KAKVEDA_KV_QUANT", "int8")
    int8 = MultiModelRuntime._engine_pool_bytes(cfg)
    import numpy as np

    itemsize = np.dtype(cfg.dtype).itemsize
    expected_ratio = (1.0 + 4.0 / cfg.head_dim) / itemsize
    assert abs(int8 / dense - expected_ratio) < 1e-6, (int8, dense)
