"""Real-weights integration (VERDICT item 8): with ``KAKVEDA_HF_DIR``
pointing at a local HF checkpoint directory, prove the whole chain —
convert → serve through the shared engine → one greedy generation with
the expected continuation — on any machine that has weights. Skipped
(not failed) when no checkpoint is available: the CI image ships none.

The hermetic half (no weights needed) pins the env wiring itself, so the
documented knob can't silently stop being read.
"""

import os

import pytest


def test_from_env_reads_hf_dir(monkeypatch):
    """KAKVEDA_HF_DIR routes from_env to the HF conversion path (alias of
    KAKVEDA_HF_CKPT, which wins when both are set)."""
    from kakveda_tpu.models.generate import LlamaRuntime

    calls = []

    @classmethod
    def fake_from_hf(cls, path, *, mesh=None, quant=None):
        calls.append((path, quant))
        return "sentinel"

    monkeypatch.setattr(LlamaRuntime, "from_hf", fake_from_hf)
    monkeypatch.delenv("KAKVEDA_HF_CKPT", raising=False)
    monkeypatch.setenv("KAKVEDA_HF_DIR", "/ckpts/some-model")
    assert LlamaRuntime.from_env() == "sentinel"
    assert calls == [("/ckpts/some-model", None)]

    monkeypatch.setenv("KAKVEDA_HF_CKPT", "/ckpts/other-model")
    LlamaRuntime.from_env()
    assert calls[-1][0] == "/ckpts/other-model"  # explicit ckpt wins


@pytest.mark.slow
@pytest.mark.skipif(
    not os.environ.get("KAKVEDA_HF_DIR"),
    reason="KAKVEDA_HF_DIR not set (needs a local HF checkpoint directory)",
)
def test_hf_dir_convert_serve_greedy_continuation():
    """convert → serve → greedy generation with an expected continuation.

    Any real language model completes the pangram; the engine path must
    also agree token-for-token with the offline fused decode (greedy
    parity — the Ollama-parity claim, proven on real weights)."""
    from kakveda_tpu.models.generate import LlamaRuntime, generate_tokens_fused

    rt = LlamaRuntime.from_env()
    prompt = os.environ.get(
        "KAKVEDA_HF_PROMPT", "The quick brown fox jumps over the lazy"
    )
    expect = os.environ.get("KAKVEDA_HF_EXPECT", "dog")

    res = rt.generate(prompt, max_tokens=8)
    assert res.meta["provider"] == "tpu"
    assert expect.lower() in res.text.lower(), (
        f"greedy continuation {res.text!r} does not contain {expect!r} — "
        "conversion or decode is wrong for this checkpoint"
    )

    # Engine (continuous batching) vs offline fused decode: same tokens.
    ids = rt.tokenizer.encode(prompt)
    offline = generate_tokens_fused(rt.params, rt.cfg, [ids], max_new_tokens=8)[0]
    offline_text = rt.tokenizer.decode(offline)
    assert res.text == offline_text, "engine decode diverged from offline greedy"
