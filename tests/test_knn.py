"""Sharded kNN numerical tests vs a NumPy oracle, on the 8-device CPU mesh."""

import numpy as np
import pytest

from kakveda_tpu.ops.knn import ShardedKnn, physical_to_slot, slot_to_physical
from kakveda_tpu.parallel.mesh import create_mesh


def _oracle_topk(corpus, q, k):
    scores = q @ corpus.T
    idx = np.argsort(-scores, axis=1, kind="stable")[:, :k]
    vals = np.take_along_axis(scores, idx, axis=1)
    return vals, idx


def _normed(rng, n, d):
    x = rng.standard_normal((n, d)).astype(np.float32)
    return x / np.linalg.norm(x, axis=1, keepdims=True)


def test_slot_physical_roundtrip():
    slots = np.arange(1000, dtype=np.int32)
    phys = slot_to_physical(slots, n_shards=8, rows_per_shard=128)
    back = physical_to_slot(phys, n_shards=8, rows_per_shard=128)
    np.testing.assert_array_equal(slots, back)
    assert len(np.unique(phys)) == 1000  # injective


@pytest.mark.parametrize("mesh_spec", ["data:1", "data:-1"])
def test_topk_matches_oracle(mesh_spec):
    mesh = create_mesh(mesh_spec)
    rng = np.random.default_rng(0)
    n, d, k, b = 200, 256, 5, 4
    knn = ShardedKnn(mesh, capacity=512, dim=d, k=k)
    emb, valid = knn.alloc()

    corpus = _normed(rng, n, d)
    slots = np.arange(n, dtype=np.int32)
    emb, valid = knn.insert(emb, valid, corpus, slots)

    q = _normed(rng, b, d)
    vals, got_slots = knn.topk(emb, valid, q)

    ov, oi = _oracle_topk(corpus, q, k)
    np.testing.assert_allclose(vals, ov, atol=1e-4)
    # Scores agree; indices agree wherever scores aren't tied.
    for row in range(b):
        assert set(got_slots[row]) == set(oi[row]) or np.allclose(
            np.sort(vals[row]), np.sort(ov[row]), atol=1e-4
        )


def test_topk_ignores_invalid_rows():
    mesh = create_mesh("data:-1")
    rng = np.random.default_rng(1)
    d, k = 128, 5
    knn = ShardedKnn(mesh, capacity=64, dim=d, k=k)
    emb, valid = knn.alloc()

    corpus = _normed(rng, 3, d)
    emb, valid = knn.insert(emb, valid, corpus, np.arange(3, dtype=np.int32))

    vals, slots = knn.topk(emb, valid, corpus[:1])
    real = vals[0] > -1.0
    assert real.sum() == 3  # only the 3 inserted rows match
    assert slots[0][0] == 0  # self-match first
    assert vals[0][0] > 0.99


def test_insert_updates_existing_slot():
    mesh = create_mesh("data:-1")
    rng = np.random.default_rng(2)
    d = 128
    knn = ShardedKnn(mesh, capacity=64, dim=d, k=3)
    emb, valid = knn.alloc()

    a = _normed(rng, 1, d)
    b = _normed(rng, 1, d)
    emb, valid = knn.insert(emb, valid, a, np.asarray([0], dtype=np.int32))
    emb, valid = knn.insert(emb, valid, b, np.asarray([0], dtype=np.int32))

    vals, slots = knn.topk(emb, valid, b)
    assert slots[0][0] == 0
    assert vals[0][0] > 0.99


def test_capacity_rounds_to_shard_multiple():
    mesh = create_mesh("data:-1")
    knn = ShardedKnn(mesh, capacity=100, dim=128, k=5)
    assert knn.capacity % mesh.shape["data"] == 0
    assert knn.capacity >= 100


def test_insert_sparse_matches_dense():
    """Sparse (idx,val) insert must produce the same index rows and type
    table as the dense path, including ragged tail batches that get padded
    to the batch bucket."""
    import jax

    from kakveda_tpu.ops.featurizer import HashedNGramFeaturizer
    from kakveda_tpu.parallel.mesh import create_mesh

    mesh = create_mesh("data:2")
    feat = HashedNGramFeaturizer(dim=256)
    texts = [
        f"intent_tags:intent:citations_required | prompt_hint:summarize doc {i} | tools: | env_keys:os"
        for i in range(5)  # odd count → bucket padding exercised
    ]
    dense = feat.encode_batch(texts)
    idx, val = feat.encode_batch_sparse(texts)
    assert idx.shape == val.shape and idx.shape[0] == 5

    slots = np.arange(5, dtype=np.int32)
    tids = np.asarray([0, 1, 0, 2, 1], np.int32)

    kd = ShardedKnn(mesh, capacity=64, dim=256, k=3)
    e1, v1 = kd.insert(*kd.alloc(), dense, slots)
    t1 = kd.scatter_i32(kd.alloc_i32(), slots, tids)

    ks = ShardedKnn(mesh, capacity=64, dim=256, k=3)
    e2, v2 = ks.alloc()
    e2, v2, t2 = ks.insert_sparse(e2, v2, ks.alloc_i32(), idx, val, slots, tids)

    np.testing.assert_allclose(np.asarray(e1), np.asarray(e2), atol=1e-6)
    np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))
    np.testing.assert_array_equal(np.asarray(t1), np.asarray(t2))

    # Matches flow identically through either index.
    q = dense[:2]
    s1, i1 = kd.topk(e1, v1, q)
    s2, i2 = ks.topk(e2, v2, q)
    np.testing.assert_allclose(s1, s2, atol=1e-5)
    np.testing.assert_array_equal(i1, i2)


def test_topk_sparse_query_matches_dense():
    """Sparse (idx,val) query dispatch must produce identical scores/slots
    to the dense path, on both single-device and sharded meshes."""
    from kakveda_tpu.ops.featurizer import HashedNGramFeaturizer
    from kakveda_tpu.parallel.mesh import create_mesh

    feat = HashedNGramFeaturizer(dim=256)
    corpus = [f"intent_tags:a,b | prompt_hint:doc {i} | tools: | env_keys:os" for i in range(9)]
    queries = corpus[:3]
    dense_rows = feat.encode_batch(corpus)
    for spec in ("data:1", "data:4"):
        knn = ShardedKnn(create_mesh(spec), capacity=32, dim=256, k=3)
        emb, valid = knn.insert(*knn.alloc(), dense_rows, np.arange(9, dtype=np.int32))
        dq = feat.encode_batch(queries)
        s1, i1 = knn.topk_result(knn.topk_async(emb, valid, dq))
        idx, val = feat.encode_batch_sparse(queries)
        # Sparse dispatch buckets ragged batches internally — rows beyond
        # the caller's batch are pad rows; slice them off.
        s2, i2 = knn.topk_result(knn.topk_async_sparse(emb, valid, idx, val))
        np.testing.assert_allclose(s1, s2[: len(queries)], atol=1e-6)
        np.testing.assert_array_equal(i1, i2[: len(queries)])
