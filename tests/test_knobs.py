"""Tier-1 guard: every KAKVEDA_* env knob the code references must be
documented (CLAUDE.md / docs/) — scripts/check_knobs.py run as a test so
an undocumented operator lever fails CI, not a 3am debugging session."""

import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]


def test_every_kakveda_knob_is_documented():
    r = subprocess.run(
        [sys.executable, str(ROOT / "scripts" / "check_knobs.py"), str(ROOT)],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, f"\n{r.stdout}\n{r.stderr}"


def test_checker_catches_an_undocumented_knob(tmp_path):
    """The checker itself works: a synthetic tree with one undocumented
    knob fails and names it."""
    (tmp_path / "kakveda_tpu").mkdir()
    (tmp_path / "kakveda_tpu" / "x.py").write_text(
        'import os\nos.environ.get("KAKVEDA_TOTALLY_NEW_KNOB")\n'
        'os.environ.get("KAKVEDA_DOCUMENTED_KNOB")\n'
    )
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "a.md").write_text("`KAKVEDA_DOCUMENTED_KNOB` does x\n")
    r = subprocess.run(
        [sys.executable, str(ROOT / "scripts" / "check_knobs.py"), str(tmp_path)],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 1
    missing = [ln.strip().split()[0] for ln in r.stdout.splitlines() if ln.startswith("  KAKVEDA_")]
    assert missing == ["KAKVEDA_TOTALLY_NEW_KNOB"]


def test_checker_catches_an_uncataloged_fault_site(tmp_path):
    """A faults.site("…") registration missing from docs/robustness.md's
    catalog fails the check — the site list grew three PRs straight with
    nothing guarding the docs."""
    (tmp_path / "kakveda_tpu").mkdir()
    (tmp_path / "kakveda_tpu" / "x.py").write_text(
        'from kakveda_tpu.core import faults as _faults\n'
        '_SITE_A = _faults.site("engine.newsite")\n'
        '_SITE_B = _faults.site("gfkb.cataloged")\n'
    )
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "robustness.md").write_text(
        "| `gfkb.cataloged` | somewhere | documented |\n"
    )
    r = subprocess.run(
        [sys.executable, str(ROOT / "scripts" / "check_knobs.py"), str(tmp_path)],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 1
    assert "engine.newsite" in r.stdout
    assert "gfkb.cataloged" not in [
        ln.strip().split()[0] for ln in r.stdout.splitlines() if ln.startswith("  ")
    ]
