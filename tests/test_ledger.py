"""Runtime compile-and-transfer ledger (core/ledger.py, KAKVEDA_LEDGER=1).

The headline test is the N-vs-log(N) pair: feeding an UNBUCKETED jit a
ragged stream of batch sizes costs one XLA compile per distinct size,
while routing the sizes through ``ops/knn.pow2_bucket`` first collapses
the stream to O(log N) compiles — the exact economics the static
retrace-hazard rule and the bench envelope assertions are built on.

Hygiene: the ledger monkeypatches ``jax.jit`` process-globally and tier-1
runs the whole suite in ONE process, so every test uninstalls + resets in
a finally (and the module-scope fixture double-checks on the way out).
"""

import sys
from pathlib import Path

import numpy as np
import pytest

ROOT = Path(__file__).resolve().parents[1]
if str(ROOT) not in sys.path:
    sys.path.insert(0, str(ROOT))

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from kakveda_tpu.core import ledger  # noqa: E402
from kakveda_tpu.ops.knn import pow2_bucket  # noqa: E402


@pytest.fixture
def installed_ledger(monkeypatch):
    """Arm + install the ledger for one test; always restore jax.jit."""
    monkeypatch.setenv("KAKVEDA_LEDGER", "1")
    ledger.reset()
    assert ledger.maybe_install()
    try:
        yield ledger
    finally:
        ledger.uninstall()
        ledger.reset()


def test_disabled_is_inert(monkeypatch):
    monkeypatch.delenv("KAKVEDA_LEDGER", raising=False)
    orig = jax.jit
    try:
        assert not ledger.enabled()
        assert not ledger.maybe_install()
        assert jax.jit is orig
        # note_transfer is a no-op attribute check when off
        ledger.note_transfer("h2d", 1 << 20)
        assert ledger.ledger_report()["transfer_bytes"] == {}
    finally:
        ledger.uninstall()
        ledger.reset()


def test_unbucketed_vs_pow2_bucketed_compiles(installed_ledger):
    """32 distinct batch sizes: raw shapes compile 32 times; pow2-bucketed
    shapes compile len({pow2 buckets}) = 6 times. This is the ledger
    measuring the exact waste the retrace-hazard rule flags statically."""

    def probe_raw(x):
        return x * 2.0

    def probe_bucketed(x):
        return x * 2.0

    raw_jit = jax.jit(probe_raw)
    buck_jit = jax.jit(probe_bucketed)

    for n in range(1, 33):
        raw_jit(jnp.zeros((n,), jnp.float32)).block_until_ready()
        bb = pow2_bucket(n)
        buck_jit(jnp.zeros((bb,), jnp.float32)).block_until_ready()

    rep = ledger.ledger_report()
    assert rep["compiles"].get("probe_raw") == 32, rep["compiles"]
    expected_buckets = len({pow2_bucket(n) for n in range(1, 33)})
    assert expected_buckets == 6  # {1, 2, 4, 8, 16, 32}
    assert rep["compiles"].get("probe_bucketed") == expected_buckets, (
        rep["compiles"]
    )


def test_entry_attribution_and_lambda_inherits(installed_ledger):
    """jits made after install self-label; a jitted lambda has no name and
    must inherit the ambient entry() label instead of masking it."""
    lam = jax.jit(lambda x: x + 3.0)
    with ledger.entry("warnpath"):
        lam(jnp.zeros((7,), jnp.float32)).block_until_ready()
    rep = ledger.ledger_report()
    assert rep["compiles"].get("warnpath") == 1, rep["compiles"]


def test_decorator_factory_form_and_donation_passthrough(installed_ledger):
    """The kwargs-only form jax.jit(donate_argnums=...) returns a factory;
    the wrapper must thread kwargs through and keep donation semantics."""

    @jax.jit
    def plain(x):
        return x + 1.0

    factory = jax.jit(donate_argnums=(0,))

    def donated(x):
        return x * 2.0

    donated_jit = factory(donated)
    x = jnp.zeros((5,), jnp.float32)
    plain(x).block_until_ready()
    donated_jit(x).block_until_ready()
    rep = ledger.ledger_report()
    assert rep["compiles"].get("plain") == 1, rep["compiles"]
    assert rep["compiles"].get("donated") == 1, rep["compiles"]


def test_mark_warm_records_post_warmup_compiles(installed_ledger):
    @jax.jit
    def step(x):
        return x - 1.0

    step(jnp.zeros((4,), jnp.float32)).block_until_ready()
    ledger.mark_warm()
    rep = ledger.ledger_report()
    assert rep["warm"] and rep["post_warmup_compiles"] == 0
    # a NEW shape after warmup is the bug the benches assert against
    step(jnp.zeros((9,), jnp.float32)).block_until_ready()
    rep = ledger.ledger_report()
    assert rep["post_warmup_compiles"] == 1, rep
    assert rep["post_warmup"][0]["fn"] == "step"
    assert rep["post_warmup"][0]["duration_ms"] >= 0


def test_transfer_phases_and_directions(installed_ledger):
    ledger.note_transfer("h2d", 1024)  # no phase active
    with ledger.phase("warn"):
        ledger.note_transfer("h2d", 4096)
        ledger.note_transfer("d2h", 256)
    with ledger.phase("ingest"):
        ledger.note_transfer("h2d", 512)
    ledger.note_transfer("d2h", 0)  # zero bytes: dropped
    rep = ledger.ledger_report()
    assert rep["transfer_by_phase"] == {
        "h2d": {"unphased": 1024, "warn": 4096, "ingest": 512},
        "d2h": {"warn": 256},
    }
    assert rep["transfer_bytes"] == {"h2d": 5632, "d2h": 256}


def test_labeled_jit_delegates_and_binds(installed_ledger):
    """_LabeledJit must stay a drop-in: attribute passthrough to the real
    jitted object and descriptor binding for decorated methods."""

    @jax.jit
    def f(x):
        return x + 1.0

    assert hasattr(f, "lower")  # delegation via __getattr__
    assert "ledger-labeled" in repr(f)

    class Eng:
        @jax.jit
        def m(self_arr):
            return self_arr * 3.0

    out = Eng.m(jnp.ones((2,), jnp.float32))  # unbound: passes arr as arg
    np.testing.assert_allclose(np.asarray(out), [3.0, 3.0])


def test_reset_keeps_install_uninstall_restores_jit(monkeypatch):
    monkeypatch.setenv("KAKVEDA_LEDGER", "1")
    orig = jax.jit
    try:
        ledger.reset()
        assert ledger.maybe_install()
        assert jax.jit is not orig

        @jax.jit
        def g(x):
            return x

        g(jnp.zeros((3,), jnp.float32)).block_until_ready()
        assert ledger.ledger_report()["compile_total"] >= 1
        ledger.reset()
        assert ledger.installed()  # reset clears tables, not the install
        assert ledger.ledger_report()["compile_total"] == 0
        ledger.uninstall()
        assert jax.jit is orig
        # deafened: compiles after uninstall are not counted
        h = jax.jit(lambda x: x * 5.0)
        h(jnp.zeros((3,), jnp.float32)).block_until_ready()
        assert ledger.ledger_report()["compile_total"] == 0
        # captured jitted callables from the installed era keep working
        g(jnp.zeros((3,), jnp.float32)).block_until_ready()
    finally:
        ledger.uninstall()
        ledger.reset()


def test_metrics_families_exported(installed_ledger):
    from kakveda_tpu.core import metrics

    @jax.jit
    def exported(x):
        return x + 2.0

    with ledger.phase("warn"):
        exported(jnp.zeros((6,), jnp.float32)).block_until_ready()
        ledger.note_transfer("d2h", 123)
    text = metrics.get_registry().render()
    assert 'kakveda_compile_total{fn="exported"}' in text
    assert 'direction="d2h"' in text and 'phase="warn"' in text
