"""Failure-memory lifecycle (ISSUE 18): checkpoint+delta compaction,
row aging/tombstones, duplicate collapse, the replication fence, and the
crash-point recovery certification (docs/robustness.md § failure-memory
lifecycle).

The contracts under test:
  * compact() swaps behind a manifest fence — reopen serves identical
    matches, `KAKVEDA_GFKB_COMPACT=0` is bit-for-bit append-only;
  * the crash-safe replay contracts (ONE torn final line tolerated,
    mid-file corruption raises) hold unchanged on a compacted log;
  * tombstones are durable-before-visible, survive restart, fence
    replicated/DLQ-replayed events, and only ORGANIC upserts resurrect;
  * the crash sweep certifies every kill offset recovers to a legal
    pre/mid/post state (chaos-marked, subprocess kills).
"""

import json
import time

import pytest

from kakveda_tpu.core import faults
from kakveda_tpu.core.schemas import Severity
from kakveda_tpu.index.gfkb import GFKB


@pytest.fixture(autouse=True)
def _disarm_faults():
    faults.disarm()
    yield
    faults.disarm()


def _mk(tmp_path, **kw):
    kw.setdefault("capacity", 64)
    kw.setdefault("dim", 256)
    return GFKB(data_dir=tmp_path / "data", **kw)


def _sig(i):
    return f"lifecycle test failure signature {i} worker shard {i % 5}"


def _seed(kb, n, apps=3):
    kb.upsert_failures_batch([
        {"failure_type": "oom" if i % 2 else "timeout",
         "signature_text": _sig(i), "app_id": f"app-{i % apps}",
         "impact_severity": Severity.high}
        for i in range(n)
    ])


# ---------------------------------------------------------------------------
# compaction
# ---------------------------------------------------------------------------


def test_compact_roundtrip_parity(tmp_path):
    kb = _mk(tmp_path)
    _seed(kb, 12)
    _seed(kb, 12)  # occurrence bumps: version-append history to fold
    before = kb.match_batch([_sig(3), _sig(8)])
    recs_before = [(r.failure_id, r.version, r.occurrences) for r in kb._records]
    bytes_before = (tmp_path / "data" / "failures.jsonl").stat().st_size

    out = kb.compact()
    assert out["compacted"] and out["generation"] == 1
    assert out["checkpoint_rows"] == 12
    assert out["bytes_after"] < bytes_before
    kb.close()

    kb2 = _mk(tmp_path)
    assert [(r.failure_id, r.version, r.occurrences) for r in kb2._records] == recs_before
    after = kb2.match_batch([_sig(3), _sig(8)])
    for a, b in zip(before, after):
        assert a and b and a[0].failure_id == b[0].failure_id
        assert abs(a[0].score - b[0].score) < 1e-5
    assert kb2.lifecycle_info()["compact_generation"] == 1
    # delta appends land AFTER the checkpoint and survive another restart
    _seed(kb2, 13)
    kb2.close()
    kb3 = _mk(tmp_path)
    assert len(kb3._records) == 13
    kb3.close()


def test_compact_optout_is_bit_for_bit(tmp_path, monkeypatch):
    kb = _mk(tmp_path)
    _seed(kb, 6)
    _seed(kb, 6)
    log = tmp_path / "data" / "failures.jsonl"
    raw = log.read_bytes()
    monkeypatch.setenv("KAKVEDA_GFKB_COMPACT", "0")
    out = kb.compact()
    assert out["compacted"] is False and "KAKVEDA_GFKB_COMPACT=0" in out["reason"]
    assert log.read_bytes() == raw  # untouched, byte for byte
    kb.close()


def test_torn_tail_contract_survives_compaction(tmp_path):
    """Post-compaction, the log is checkpoint+delta — the torn-FINAL-line
    tolerance (warn + truncate-on-next-append) must hold on the delta."""
    kb = _mk(tmp_path)
    _seed(kb, 4)
    assert kb.compact()["compacted"]
    _seed(kb, 5)  # one delta line past the checkpoint
    kb.close()

    log = tmp_path / "data" / "failures.jsonl"
    with log.open("ab") as f:
        f.write(b'{"failure_type": "torn", "signa')

    kb2 = _mk(tmp_path)  # warns, does not raise
    assert len(kb2._records) == 5
    _seed(kb2, 6)  # next append truncates the torn bytes first
    kb2.close()
    for line in log.read_text().splitlines():
        json.loads(line)
    kb3 = _mk(tmp_path)
    assert len(kb3._records) == 6
    kb3.close()


def test_midfile_corruption_in_delta_still_raises(tmp_path):
    kb = _mk(tmp_path)
    _seed(kb, 3)
    assert kb.compact()["compacted"]
    _seed(kb, 5)  # two delta lines
    kb.close()
    log = tmp_path / "data" / "failures.jsonl"
    lines = log.read_text().splitlines()
    assert len(lines) >= 2
    lines.insert(1, '{"torn": "mid-file')
    log.write_text("\n".join(lines) + "\n")
    with pytest.raises(ValueError, match="mid-file"):
        _mk(tmp_path)


def test_auto_compact_trigger(tmp_path, monkeypatch):
    monkeypatch.setenv("KAKVEDA_GFKB_COMPACT_BYTES", "1")
    kb = _mk(tmp_path)
    _seed(kb, 8)  # post-batch check sees size >= 1 byte -> background compact
    deadline = time.time() + 15
    while time.time() < deadline:
        if kb.lifecycle_info()["compact_generation"] >= 1:
            break
        time.sleep(0.05)
    assert kb.lifecycle_info()["compact_generation"] >= 1
    # compacted store still serves and still accepts appends
    assert kb.match_batch([_sig(2)])[0]
    _seed(kb, 9)
    kb.close()


def test_applied_log_stale_tmp_removed_at_startup(tmp_path):
    kb = _mk(tmp_path)
    row = {"failure_type": "oom", "signature_text": _sig(0),
           "app_id": "app-peer", "impact_severity": "high"}
    kb.apply_replication([row], event_id="evt-stale-tmp")
    kb.close()
    # crash window: tmp written, os.replace never ran — the old log is live
    stale = tmp_path / "data" / "applied_events.tmp"
    stale.write_text('{"id": "half-written')
    kb2 = _mk(tmp_path)  # startup compaction removes the stranded temp
    assert not stale.exists()
    # and the dedup evidence from the REAL log still fences the event
    assert kb2.apply_replication([row], event_id="evt-stale-tmp") == 0
    kb2.close()


# ---------------------------------------------------------------------------
# aging, resurrection, collapse
# ---------------------------------------------------------------------------


def test_aging_tombstones_and_organic_resurrection_across_restart(tmp_path):
    kb = _mk(tmp_path)
    _seed(kb, 6)
    future = time.time() + 10_000
    out = kb.age_rows(ttl_s=100, now=future)
    assert out["tombstoned"] == 6
    info = kb.lifecycle_info()
    assert info["tombstoned"] == 6 and info["by_reason"] == {"aged": 6}
    # tombstoned rows never match …
    assert all(
        not m or m[0].score < 0.5 for m in kb.match_batch([_sig(0), _sig(1)])
    )
    # … and never ship to shard peers
    rows, _ = kb.export_rows()
    assert rows == []
    kb.close()

    kb2 = _mk(tmp_path)  # tombstones replay across restart
    assert kb2.lifecycle_info()["tombstoned"] == 6
    # ORGANIC upsert resurrects with history intact
    rec, created = kb2.upsert_failure(
        failure_type="oom", signature_text=_sig(1), app_id="app-new",
        impact_severity=Severity.high,
    )
    assert not created and rec.occurrences == 2
    assert kb2.lifecycle_info()["tombstoned"] == 5
    m = kb2.match_batch([_sig(1)])[0]
    assert m and m[0].failure_id == rec.failure_id and m[0].score > 0.9
    kb2.close()

    kb3 = _mk(tmp_path)  # the "live" op line replays too
    assert kb3.lifecycle_info()["tombstoned"] == 5
    assert kb3.match_batch([_sig(1)])[0][0].failure_id == rec.failure_id
    kb3.close()


def test_collapse_duplicates_folds_cluster_into_exemplar(tmp_path):
    kb = _mk(tmp_path, dim=1024)
    family = [
        ("timeout", f"timeout while calling payments api attempt {i}", f"app-{i}")
        for i in range(3)
    ]
    for ftype, sig, app in family:
        kb.upsert_failure(
            failure_type=ftype, signature_text=sig, app_id=app,
            impact_severity=Severity.medium,
        )
    kb.upsert_failure(
        failure_type="schema", signature_text="totally different shape xyz",
        app_id="app-solo", impact_severity=Severity.medium,
    )
    out = kb.collapse_duplicates(min_cluster=3)
    assert out["clusters"] == 1 and out["collapsed"] == 2
    info = kb.lifecycle_info()
    assert info["by_reason"] == {"collapsed": 2}
    # exemplar carries the folded history; victims stopped matching
    ex = kb._records[0]
    assert ex.occurrences == 3
    assert set(ex.affected_apps) == {"app-0", "app-1", "app-2"}
    m = kb.match_batch(["timeout while calling payments api attempt 2"])[0]
    assert m and m[0].failure_id == ex.failure_id
    # the singleton is untouched
    assert kb.match_batch(["totally different shape xyz"])[0][0].score > 0.9
    kb.close()
    kb2 = _mk(tmp_path, dim=1024)  # fold + tombstones replay
    assert kb2._records[0].occurrences == 3
    assert kb2.lifecycle_info()["tombstoned"] == 2
    kb2.close()


def test_collapse_refuses_on_stale_mine_state(tmp_path, monkeypatch):
    monkeypatch.setenv("KAKVEDA_MINE_INCREMENTAL", "0")
    kb = _mk(tmp_path)
    _seed(kb, 4)
    out = kb.collapse_duplicates(min_cluster=2)
    assert out["collapsed"] == 0 and "reason" in out
    kb.close()


# ---------------------------------------------------------------------------
# replication fence
# ---------------------------------------------------------------------------


def test_replicated_event_never_resurrects_tombstoned_row(tmp_path):
    kb = _mk(tmp_path)
    _seed(kb, 3)
    kb.age_rows(ttl_s=100, now=time.time() + 10_000)
    assert kb.lifecycle_info()["tombstoned"] == 3
    row = {
        "failure_type": "timeout", "signature_text": _sig(0),
        "app_id": "app-peer", "impact_severity": "high",
    }
    # DLQ-replayed shape: replicated event id -> fenced, clean no-op
    kb.apply_replication([row], event_id="evt-dlq-1")
    assert kb.lifecycle_info()["tombstoned"] == 3
    assert kb._records[0].occurrences == 1  # no bump through the fence
    kb.close()

    kb2 = _mk(tmp_path)  # fence state survives restart
    kb2.apply_replication([row], event_id="evt-dlq-2")
    assert kb2.lifecycle_info()["tombstoned"] == 3
    assert kb2._records[0].occurrences == 1
    # organic traffic (no event id) DOES resurrect
    rec, _ = kb2.upsert_failure(
        failure_type="timeout", signature_text=_sig(0), app_id="app-peer",
        impact_severity=Severity.high,
    )
    assert rec.occurrences == 2
    assert kb2.lifecycle_info()["tombstoned"] == 2
    kb2.close()


# ---------------------------------------------------------------------------
# chaos: fault sites + the crash-point sweep
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_tombstone_write_fault_leaves_rows_live(tmp_path):
    """gfkb.tombstone contract: the transition that never hit disk never
    happened — the faulted row (and the rest of the pass) stays LIVE,
    age_rows reports fewer rows, nothing raises."""
    kb = _mk(tmp_path)
    _seed(kb, 4)
    faults.arm("gfkb.tombstone:1.0:1")
    out = kb.age_rows(ttl_s=100, now=time.time() + 10_000)
    assert out["tombstoned"] == 0  # first write faulted -> pass stopped
    assert kb.lifecycle_info()["tombstoned"] == 0
    faults.disarm()
    assert kb.age_rows(ttl_s=100, now=time.time() + 10_000)["tombstoned"] == 4
    kb.close()


@pytest.mark.chaos
def test_compact_fault_keeps_old_log_live(tmp_path):
    """A fault while writing the compacted delta aborts the swap with the
    old (manifest, log) pair fully live — replay is unaffected."""
    kb = _mk(tmp_path)
    _seed(kb, 5)
    log = tmp_path / "data" / "failures.jsonl"
    raw = log.read_bytes()
    faults.arm("gfkb.compact_delta:1.0:1")
    with pytest.raises(Exception):
        kb.compact()
    faults.disarm()
    assert log.read_bytes() == raw
    assert kb.lifecycle_info()["compact_generation"] == 0
    kb.close()
    kb2 = _mk(tmp_path)
    assert len(kb2._records) == 5
    assert kb2.compact()["compacted"]  # next attempt succeeds cleanly
    kb2.close()


@pytest.mark.chaos
def test_crash_sweep_certifies_compaction_windows():
    """Subprocess kill at each compaction fence boundary; the recovered
    store must equal a legal pre/mid/post oracle with top-1 parity. The
    full site list runs in the `recovery` bench row — this keeps the
    tier-1 cost to the two fence-critical windows."""
    from kakveda_tpu.index.crashsweep import run_sweep

    out = run_sweep(
        rows=6, aged=3,
        sites=("gfkb.compact_fence", "gfkb.compact_swap"),
    )
    assert out["corrupt_recoveries"] == 0, out["failures"]
    assert out["kill_points"] >= 2
    assert out["stable_queries"]
