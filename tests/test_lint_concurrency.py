"""The concurrency pass (kakveda_tpu/analysis/concurrency.py,
docs/static-analysis.md): four rules — lockset-race, lock-order,
event-loop-blocking, unjoined-thread — each proven against a known-bad
fixture AND its known-good twin, plus real-tree mutation tests (delete a
live guard / wrapper from a shipped file, the rule must fire) so the
rules demonstrably cover the code they were written for.

No jax: the analysis package is pure stdlib AST.
"""

import subprocess
import sys
import textwrap
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]
if str(ROOT) not in sys.path:
    sys.path.insert(0, str(ROOT))

from kakveda_tpu.analysis.framework import run_lint  # noqa: E402

CONCURRENCY_RULES = ("lockset-race", "lock-order", "event-loop-blocking",
                     "unjoined-thread")


def _tree(tmp_path: Path, files: dict) -> Path:
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return tmp_path


def _findings(root: Path, rule: str):
    return run_lint(root, rule_ids=[rule]).findings


# ---------------------------------------------------------------------------
# the tree itself
# ---------------------------------------------------------------------------


def test_tree_is_clean_under_concurrency_rules():
    """The shipped tree passes all four rules with zero live findings —
    the PR that introduced them triaged and fixed what they found — and
    the pass stays inside its wall budget."""
    t0 = time.perf_counter()
    res = run_lint(ROOT, rule_ids=list(CONCURRENCY_RULES))
    wall = time.perf_counter() - t0
    assert res.findings == [], "\n".join(f.human() for f in res.findings)
    assert wall < 5.0, f"concurrency pass took {wall:.1f}s — budget is 5s"


def test_runtime_lock_names_match_static_graph_nodes():
    """Every sanitize.named_lock("…") literal in the tree IS a node the
    static analyzer can produce (ClassName._attr / module._name) — the
    equality the runtime/static cross-check rides on."""
    import re

    from kakveda_tpu.analysis import discovery

    names = set()
    for p in discovery.code_files(ROOT):
        if p.name in ("sanitize.py", "concurrency.py"):
            continue  # define/document named_lock; docstrings show "…" usage
        for m in re.finditer(r'named_lock\(\s*"([^"]+)"', p.read_text()):
            names.add(m.group(1))
    assert names, "the tree constructs its locks through named_lock"
    for n in names:
        assert re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]*\.[A-Za-z_][A-Za-z0-9_]*", n), n


# ---------------------------------------------------------------------------
# lockset-race
# ---------------------------------------------------------------------------

_RACY = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []

        def put(self, x):
            with self._lock:
                self._items.append(x)

        def drop(self):
            self._items.clear()
"""


def test_lockset_race_flags_unguarded_mutation(tmp_path):
    root = _tree(tmp_path, {"kakveda_tpu/box.py": _RACY})
    fs = _findings(root, "lockset-race")
    assert len(fs) == 1 and "Box._items" in fs[0].message, fs


def test_lockset_race_good_twin_passes(tmp_path):
    root = _tree(tmp_path, {"kakveda_tpu/box.py": _RACY.replace(
        "            self._items.clear()",
        "            with self._lock:\n                self._items.clear()",
    )})
    assert _findings(root, "lockset-race") == []


def test_lockset_race_owned_by_annotation_suppresses(tmp_path):
    """owned-by[<context>] on the __init__ declaration documents a
    single-writer field — the rule stands down (an annotation, not a
    silent suppression: greps for owned-by find it)."""
    root = _tree(tmp_path, {"kakveda_tpu/box.py": _RACY.replace(
        "            self._items = []",
        "            # kakveda: owned-by[caller] — single-writer by design\n"
        "            self._items = []",
    )})
    assert _findings(root, "lockset-race") == []


def test_lockset_race_caller_held_guard_propagates(tmp_path):
    """A private helper mutating state is guarded by its CALL SITE's
    ``with`` — the gfkb reload()/_replay() shape must not be flagged."""
    root = _tree(tmp_path, {"kakveda_tpu/kb.py": """
        import threading

        class KB:
            def __init__(self):
                self._lock = threading.Lock()
                self._rows = []

            def reload(self):
                with self._lock:
                    self._replay()

            def add(self, r):
                with self._lock:
                    self._rows.append(r)

            def _replay(self):
                self._rows.clear()
    """})
    assert _findings(root, "lockset-race") == []


def test_lockset_race_multi_context_unguarded(tmp_path):
    """A field mutated from BOTH a spawned thread and the caller's thread
    with no lock anywhere is variant (b): multiple contexts, no common
    guard."""
    root = _tree(tmp_path, {"kakveda_tpu/w.py": """
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self._guarded = []
                self._out = []

            def start(self):
                t = threading.Thread(target=self._run, daemon=True)
                t.start()

            def _run(self):
                self._out.append(1)

            def push(self, x):
                self._out.append(x)

            def note(self, x):
                with self._lock:
                    self._guarded.append(x)
    """})
    fs = _findings(root, "lockset-race")
    assert len(fs) == 1 and "Worker._out" in fs[0].message, fs
    assert "multiple contexts" in fs[0].message


def test_lockset_race_real_tree_mutation_gossip():
    """Delete the ``with self._lock`` guards from the shipped
    fleet/gossip.py FleetView — the rule must fire on the now-unguarded
    mutations (proof the rule covers the real file, not just fixtures)."""
    import re

    src = (ROOT / "kakveda_tpu/fleet/gossip.py").read_text()
    lines = src.splitlines(keepends=True)
    out, i, dropped = [], 0, 0
    while i < len(lines):
        ln = lines[i]
        m = re.match(r"^(\s*)with self\._lock:\s*$", ln)
        if m:
            # Drop the with-line, dedent its body by 4.
            indent = len(m.group(1))
            i += 1
            while i < len(lines):
                body = lines[i]
                if body.strip() and (len(body) - len(body.lstrip())) <= indent:
                    break
                out.append(body[4:] if body.startswith(" " * (indent + 4))
                           else body)
                i += 1
            dropped += 1
            continue
        out.append(ln)
        i += 1
    assert dropped >= 1, "gossip.py no longer guards with self._lock?"
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        root = Path(td)
        (root / "kakveda_tpu/fleet").mkdir(parents=True)
        (root / "kakveda_tpu/fleet/gossip.py").write_text("".join(out))
        fs = _findings(root, "lockset-race")
    assert any("FleetView" in f.message for f in fs), fs


# ---------------------------------------------------------------------------
# lock-order
# ---------------------------------------------------------------------------

_INVERTED = """
    import threading

    class Runtime:
        def __init__(self):
            self._load_lock = threading.Lock()
            self._lru_lock = threading.Lock()

        def load(self):
            with self._load_lock:
                with self._lru_lock:
                    pass

        def evict(self):
            with self._lru_lock:
                with self._load_lock:
                    pass
"""


def test_lock_order_flags_inverted_nesting(tmp_path):
    """The inverted MultiModelRuntime-style nesting (load: A->B,
    evict: B->A) is a deadlock-in-waiting — exactly one cycle finding."""
    root = _tree(tmp_path, {"kakveda_tpu/rt.py": _INVERTED})
    fs = _findings(root, "lock-order")
    assert len(fs) == 1, fs
    assert "lock-order cycle" in fs[0].message
    assert "Runtime._load_lock" in fs[0].message
    assert "Runtime._lru_lock" in fs[0].message


def test_lock_order_consistent_nesting_passes(tmp_path):
    root = _tree(tmp_path, {"kakveda_tpu/rt.py": _INVERTED.replace(
        """        def evict(self):
            with self._lru_lock:
                with self._load_lock:
                    pass""",
        """        def evict(self):
            with self._load_lock:
                with self._lru_lock:
                    pass""",
    )})
    assert _findings(root, "lock-order") == []


def test_lock_order_sees_transitive_acquisition(tmp_path):
    """A cycle THROUGH a method call (hold A, call something that takes
    B; elsewhere hold B then take A) is still a cycle — lexical nesting
    alone would miss it."""
    root = _tree(tmp_path, {"kakveda_tpu/tr.py": """
        import threading

        class T:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def fwd(self):
                with self._a_lock:
                    self._take_b()

            def _take_b(self):
                with self._b_lock:
                    pass

            def rev(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
    """})
    fs = _findings(root, "lock-order")
    assert len(fs) == 1 and "lock-order cycle" in fs[0].message, fs


def test_static_lock_graph_has_real_edges_and_no_cycles():
    """The shipped tree's graph contains the known-good
    MultiModelRuntime._load_lock -> _lru_lock edge and stays acyclic."""
    from kakveda_tpu.analysis.concurrency import static_lock_graph
    from kakveda_tpu.core.sanitize import find_cycles

    edges = static_lock_graph(ROOT)
    assert ("MultiModelRuntime._load_lock", "MultiModelRuntime._lru_lock") in edges
    assert find_cycles(edges) == []


# ---------------------------------------------------------------------------
# event-loop-blocking
# ---------------------------------------------------------------------------


def test_event_loop_blocking_flags_sync_calls(tmp_path):
    root = _tree(tmp_path, {"kakveda_tpu/service/h.py": """
        import time

        async def handler(request):
            time.sleep(0.1)
            data = request.path.read_text(encoding="utf-8")
            return data
    """})
    fs = _findings(root, "event-loop-blocking")
    msgs = "\n".join(f.message for f in fs)
    assert len(fs) == 2, fs
    assert "time.sleep" in msgs and "read_text" in msgs


def test_event_loop_blocking_executor_thunk_exempt(tmp_path):
    """The fix idiom — the blocking call inside the nested def/lambda
    handed to run_in_executor — must NOT be flagged (nested function
    bodies run off the loop)."""
    root = _tree(tmp_path, {"kakveda_tpu/service/h.py": """
        import asyncio

        async def handler(request):
            loop = asyncio.get_running_loop()
            data = await loop.run_in_executor(
                None, lambda: request.path.read_text(encoding="utf-8")
            )
            await asyncio.sleep(0.01)
            return data
    """})
    assert _findings(root, "event-loop-blocking") == []


def test_event_loop_blocking_real_tree_mutation_routes_main():
    """Strip the run_in_executor wrap from the shipped dashboard
    failure_detail handler (back to a bare read_text on the loop) — the
    rule must fire on the regression."""
    import tempfile

    src = (ROOT / "kakveda_tpu/dashboard/routes_main.py").read_text()
    wrapped = (
        "raw = await asyncio.get_running_loop().run_in_executor(\n"
        "                None, lambda: plat.gfkb.failures_path.read_text(encoding=\"utf-8\")\n"
        "            )"
    )
    assert wrapped in src, "routes_main.py executor wrap moved — update test"
    mutated = src.replace(
        wrapped, 'raw = plat.gfkb.failures_path.read_text(encoding="utf-8")')
    with tempfile.TemporaryDirectory() as td:
        root = Path(td)
        (root / "kakveda_tpu/dashboard").mkdir(parents=True)
        (root / "kakveda_tpu/dashboard/routes_main.py").write_text(mutated)
        fs = _findings(root, "event-loop-blocking")
    assert any("read_text" in f.message for f in fs), fs


def test_event_loop_blocking_worker_held_lock_in_async(tmp_path):
    """`with self._lock:` inside an async body, where the same file's
    spawned worker thread also takes that lock, parks the loop behind
    the worker — flagged."""
    root = _tree(tmp_path, {"kakveda_tpu/service/s.py": """
        import threading

        class Svc:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def start(self):
                threading.Thread(target=self._work, daemon=True).start()

            def _work(self):
                with self._lock:
                    self._n += 1

            async def handle(self, request):
                with self._lock:
                    return self._n
    """})
    fs = _findings(root, "event-loop-blocking")
    assert len(fs) == 1 and "Svc._lock" in fs[0].message, fs


# ---------------------------------------------------------------------------
# unjoined-thread
# ---------------------------------------------------------------------------


def test_unjoined_thread_flags_leak(tmp_path):
    root = _tree(tmp_path, {"kakveda_tpu/t.py": """
        import threading

        def go():
            t = threading.Thread(target=print)
            t.start()
    """})
    fs = _findings(root, "unjoined-thread")
    assert len(fs) == 1 and "threading.Thread" in fs[0].message, fs


def test_unjoined_thread_good_twins_pass(tmp_path):
    """daemon=True kwarg, later `.daemon = True`, a join() on a close
    path, and a cancel()'d Timer handle are all retired — no findings."""
    root = _tree(tmp_path, {"kakveda_tpu/t.py": """
        import threading

        def kwarg():
            threading.Thread(target=print, daemon=True).start()

        def attr():
            t = threading.Thread(target=print)
            t.daemon = True
            t.start()

        class C:
            def start(self):
                self._t = threading.Thread(target=print)
                self._t.start()
                self._timer = threading.Timer(1.0, print)
                self._timer.start()

            def close(self):
                self._t.join()
                self._timer.cancel()
    """})
    assert _findings(root, "unjoined-thread") == []


# ---------------------------------------------------------------------------
# --changed pre-commit mode
# ---------------------------------------------------------------------------


def test_changed_mode_scans_only_git_dirty_files(tmp_path):
    """--changed lints the git-dirty subset with per-file rules only:
    a racy untracked file fails (exit 1); tree rules (knob-docs et al.)
    are skipped so the partial corpus can't misfire."""
    subprocess.run(["git", "init", "-q", str(tmp_path)], check=True, timeout=10)
    _tree(tmp_path, {"kakveda_tpu/box.py": _RACY})
    script = ROOT / "scripts" / "lint_invariants.py"
    r = subprocess.run(
        [sys.executable, str(script), str(tmp_path), "--changed"],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 1, r.stdout + r.stderr
    assert "lockset-race" in r.stdout
    assert "knob-docs" not in r.stdout

    # Fix the file -> clean exit 0; and a clean checkout (nothing dirty)
    # short-circuits without scanning anything.
    (tmp_path / "kakveda_tpu/box.py").write_text(textwrap.dedent(
        _RACY.replace(
            "            self._items.clear()",
            "            with self._lock:\n                self._items.clear()",
        )))
    r = subprocess.run(
        [sys.executable, str(script), str(tmp_path), "--changed"],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 0, r.stdout + r.stderr
