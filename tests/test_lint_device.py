"""Tier-1 guard for the device-plane hygiene pass
(kakveda_tpu/analysis/device.py, docs/static-analysis.md).

Two layers, mirroring test_lint_invariants.py:

* **Fixture twins** — per rule, a known-bad fixture produces exactly the
  expected finding and its known-good twin passes (false-negative AND
  false-positive guard as the rules evolve).
* **Real-tree mutations** — the shipped sources, copied and minimally
  broken the way the bug would actually be written (strip the pow2
  bucket from ``topk_async_sparse``; read a donated cache after
  ``_step_chunk_jit``), must trip the rule — proof the rules are not
  vacuous on the real call graph, the same evidence standard the
  concurrency pass set.

Deliberately imports no jax: the analysis package is pure stdlib AST.
"""

import sys
import textwrap
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]
if str(ROOT) not in sys.path:
    sys.path.insert(0, str(ROOT))

from kakveda_tpu.analysis.framework import all_rules, run_lint  # noqa: E402

_DEVICE_RULES = ("constant-capture", "donation-after-use",
                 "dynamic-slice-by-trace", "host-sync", "retrace-hazard")


def _tree(tmp_path: Path, files: dict) -> Path:
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return tmp_path


def _findings(root: Path, rule: str):
    return run_lint(root, rule_ids=[rule]).findings


def _mutated_tree(tmp_path: Path, rel: str, old: str, new: str) -> Path:
    """Copy ONE real source file into a scratch tree at its repo-relative
    path, with ``old`` replaced by ``new`` (old must exist — a refactor
    that renames the anchor must update the mutation too)."""
    src = (ROOT / rel).read_text()
    assert old in src, f"mutation anchor vanished from {rel}: {old!r}"
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(src.replace(old, new))
    return tmp_path


# ---------------------------------------------------------------------------
# registry shape: every device rule is per-file scoped (so --changed runs it)
# ---------------------------------------------------------------------------


def test_device_rules_registered_and_changed_eligible():
    rules = all_rules()
    for rid in _DEVICE_RULES:
        assert rid in rules, f"device rule {rid} not registered"
        assert rules[rid].scope is not None, (
            f"{rid} must be per-file scoped so `lint_invariants.py --changed` "
            f"(the pre-commit mode) runs it"
        )


# ---------------------------------------------------------------------------
# retrace-hazard
# ---------------------------------------------------------------------------

_RETRACE_BAD = {
    "kakveda_tpu/models/pipe.py": """
    import jax
    import numpy as np

    def _impl(q):
        return q * 2

    _match_jit = jax.jit(_impl)

    def serve(rows):
        b = len(rows)
        q = np.zeros((b, 4), np.float32)
        return _match_jit(q)
    """,
}

_RETRACE_GOOD = {
    "kakveda_tpu/models/pipe.py": """
    import jax
    import numpy as np
    from kakveda_tpu.ops.knn import batch_bucket

    def _impl(q):
        return q * 2

    _match_jit = jax.jit(_impl)

    def serve(rows):
        b = batch_bucket(len(rows))
        q = np.zeros((b, 4), np.float32)
        return _match_jit(q)
    """,
}


def test_retrace_hazard_fires_on_unbucketed_shape(tmp_path):
    fs = _findings(_tree(tmp_path, _RETRACE_BAD), "retrace-hazard")
    assert len(fs) == 1, fs
    assert "_match_jit" in fs[0].message and "q" in fs[0].message


def test_retrace_hazard_good_twin_bucketed(tmp_path):
    assert _findings(_tree(tmp_path, _RETRACE_GOOD), "retrace-hazard") == []


def test_retrace_hazard_real_tree_mutation(tmp_path):
    """Strip the pow2 bucket from the REAL topk_async_sparse: the ragged
    batch size then flows raw into the pad-array shapes handed to the
    _topk_sparse jit entry — the exact regression the rule exists for."""
    rel = "kakveda_tpu/ops/knn.py"
    root = _mutated_tree(
        tmp_path, rel,
        "bb = batch_bucket(max(b, 1))",
        "bb = max(b, 1)",
    )
    fs = _findings(root, "retrace-hazard")
    assert any(f.file == rel and "_topk_sparse" in f.message for f in fs), fs
    # control: the unmutated file is clean
    assert _findings(_mutated_tree(
        tmp_path / "ctl", rel, "bb = batch_bucket(max(b, 1))",
        "bb = batch_bucket(max(b, 1))",
    ), "retrace-hazard") == []


# ---------------------------------------------------------------------------
# donation-after-use
# ---------------------------------------------------------------------------

_DONATE_COMMON = """
    import jax
    from functools import partial

    @partial(jax.jit, donate_argnums=(0,))
    def _step(cache, tok):
        return cache + tok, tok
"""

_DONATE_BAD = {
    "kakveda_tpu/models/eng.py": _DONATE_COMMON + """
    def run(cache, tok):
        new_cache, out = _step(cache, tok)
        stale = cache.sum()
        return new_cache, out, stale
    """,
}

_DONATE_GOOD = {
    "kakveda_tpu/models/eng.py": _DONATE_COMMON + """
    def run(cache, tok):
        cache, out = _step(cache, tok)
        fresh = cache.sum()
        return cache, out, fresh
    """,
}


def test_donation_after_use_fires_on_stale_read(tmp_path):
    fs = _findings(_tree(tmp_path, _DONATE_BAD), "donation-after-use")
    assert len(fs) == 1, fs
    assert "donated" in fs[0].message and "_step" in fs[0].message


def test_donation_after_use_good_twin_same_statement_rebind(tmp_path):
    assert _findings(_tree(tmp_path, _DONATE_GOOD), "donation-after-use") == []


def test_donation_after_use_real_tree_mutation(tmp_path):
    """Bind the REAL _step_chunk_jit result away from self.cache and read
    the donated cache afterwards — the sanctioned same-statement rebind is
    what keeps the shipped dispatch_chunk legal; break it and the rule
    must fire."""
    rel = "kakveda_tpu/models/serving.py"
    root = _mutated_tree(
        tmp_path, rel,
        "self.cache, self.last, _, self.rng, toks = _step_chunk_jit(",
        "stale_cache, self.last, _, self.rng, toks = _step_chunk_jit(",
    )
    # add a post-call read of the donated attr inside the same method
    p = root / rel
    src = p.read_text()
    anchor = "self._pos_np += self.chunk_steps  # every slot advances in lockstep"
    assert anchor in src
    p.write_text(src.replace(
        anchor, anchor + "\n        _stale = self.cache.shape"
    ))
    fs = _findings(root, "donation-after-use")
    assert any(
        f.file == rel and "_step_chunk_jit" in f.message
        and "self.cache" in f.message
        for f in fs
    ), fs


def test_donation_real_tree_is_clean(tmp_path):
    """The shipped serving.py/knn.py donation sites are all sanctioned
    same-statement rebinds."""
    for rel in ("kakveda_tpu/models/serving.py", "kakveda_tpu/ops/knn.py"):
        root = _mutated_tree(tmp_path / rel.replace("/", "_"), rel, "import", "import")
        assert _findings(root, "donation-after-use") == []


# ---------------------------------------------------------------------------
# constant-capture
# ---------------------------------------------------------------------------

_CAPTURE_BAD = {
    "kakveda_tpu/models/tab.py": """
    import jax
    import numpy as np

    _TABLE = np.eye(4, dtype=np.float32)

    @jax.jit
    def apply(x):
        return x @ _TABLE
    """,
}

_CAPTURE_GOOD = {
    "kakveda_tpu/models/tab.py": """
    import jax
    import numpy as np

    _TABLE = np.eye(4, dtype=np.float32)

    @jax.jit
    def apply(x, table):
        return x @ table

    def run(x):
        return apply(x, _TABLE)
    """,
}


def test_constant_capture_fires_on_closed_over_numpy(tmp_path):
    fs = _findings(_tree(tmp_path, _CAPTURE_BAD), "constant-capture")
    assert len(fs) == 1, fs
    assert "_TABLE" in fs[0].message and "closes over" in fs[0].message


def test_constant_capture_good_twin_passes_as_arg(tmp_path):
    assert _findings(_tree(tmp_path, _CAPTURE_GOOD), "constant-capture") == []


def test_constant_capture_real_tree_mutation(tmp_path):
    """Graft a module-level numpy table + a jit body closing over it onto
    the REAL ops/knn.py — the rule must catch it amid the full file."""
    rel = "kakveda_tpu/ops/knn.py"
    root = _mutated_tree(tmp_path, rel, "import", "import")
    p = root / rel
    p.write_text(p.read_text() + textwrap.dedent("""

        _MUTATION_TAB = np.arange(8, dtype=np.float32)

        @jax.jit
        def _mutation_capture(x):
            return x + _MUTATION_TAB
    """))
    fs = _findings(root, "constant-capture")
    assert any("_MUTATION_TAB" in f.message for f in fs), fs


# ---------------------------------------------------------------------------
# dynamic-slice-by-trace
# ---------------------------------------------------------------------------

_DSLICE_BAD = {
    "kakveda_tpu/models/sl.py": """
    import jax

    @jax.jit
    def take(x, n):
        return x[:n]
    """,
}

_DSLICE_GOOD = {
    "kakveda_tpu/models/sl.py": """
    import jax
    from functools import partial

    @partial(jax.jit, static_argnames=("n",))
    def take(x, n):
        return x[:n]

    @jax.jit
    def head(x, n):
        return jax.lax.dynamic_slice_in_dim(x, n, 4)
    """,
}


def test_dynamic_slice_fires_on_traced_size(tmp_path):
    fs = _findings(_tree(tmp_path, _DSLICE_BAD), "dynamic-slice-by-trace")
    assert len(fs) == 1, fs
    assert "n" in fs[0].message and "take" in fs[0].message


def test_dynamic_slice_good_twin_static_or_traced_start(tmp_path):
    """static_argnames sizes and traced STARTS (fixed size) are both fine."""
    assert _findings(_tree(tmp_path, _DSLICE_GOOD), "dynamic-slice-by-trace") == []


def test_dynamic_slice_real_tree_mutation(tmp_path):
    """Graft a traced-size dynamic_slice_in_dim body onto the REAL
    ops/knn.py."""
    rel = "kakveda_tpu/ops/knn.py"
    root = _mutated_tree(tmp_path, rel, "import", "import")
    p = root / rel
    p.write_text(p.read_text() + textwrap.dedent("""

        @jax.jit
        def _mutation_slice(x, n):
            return jax.lax.dynamic_slice_in_dim(x, 0, n)
    """))
    fs = _findings(root, "dynamic-slice-by-trace")
    assert any("_mutation_slice" in f.message for f in fs), fs


# ---------------------------------------------------------------------------
# host-sync (relocated into the device pass; fixture twins live in
# test_lint_invariants.py — here: real-tree mutation + lambda coverage)
# ---------------------------------------------------------------------------


def test_host_sync_real_tree_mutation(tmp_path):
    """Graft a np.asarray host-sync into a jit body on the REAL knn.py."""
    rel = "kakveda_tpu/ops/knn.py"
    root = _mutated_tree(tmp_path, rel, "import", "import")
    p = root / rel
    p.write_text(p.read_text() + textwrap.dedent("""

        @jax.jit
        def _mutation_sync(x):
            return np.asarray(x) + 1
    """))
    fs = _findings(root, "host-sync")
    assert any("np.asarray" in f.message for f in fs), fs


def test_host_sync_covers_jit_wrapped_lambda(tmp_path):
    fs = _findings(_tree(tmp_path, {
        "kakveda_tpu/ops/lam.py": """
        import jax

        _f = jax.jit(lambda x: float(x) + 1.0)
        """,
    }), "host-sync")
    assert len(fs) == 1, fs
    assert "float" in fs[0].message


# ---------------------------------------------------------------------------
# the shipped tree is clean under the whole device pass
# ---------------------------------------------------------------------------


def test_real_tree_clean_under_device_rules():
    res = run_lint(ROOT, rule_ids=list(_DEVICE_RULES))
    assert res.findings == [], [f.human() for f in res.findings]
