"""Tier-1 guard: the invariant linter (scripts/lint_invariants.py,
docs/static-analysis.md) runs CLEAN over the tree, and every rule provably
detects its target violation — a known-bad fixture per rule must produce
exactly the expected finding and its known-good twin must pass, guarding
against false negatives AND false positives as the rules evolve.

Deliberately imports no jax: the analysis package is pure stdlib AST, and
this file must stay runnable (and fast — the whole-tree run is budgeted
< 10 s) without a backend.
"""

import json
import subprocess
import sys
import textwrap
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]
if str(ROOT) not in sys.path:
    sys.path.insert(0, str(ROOT))

from kakveda_tpu.analysis.framework import run_lint  # noqa: E402


def _tree(tmp_path: Path, files: dict) -> Path:
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return tmp_path


def _findings(root: Path, rule: str):
    return run_lint(root, rule_ids=[rule]).findings


# ---------------------------------------------------------------------------
# the tree itself
# ---------------------------------------------------------------------------


def test_tree_is_clean_and_fast():
    """The shipped tree passes every rule (exit 0) well inside the tier-1
    budget — and the committed baseline stays empty."""
    t0 = time.perf_counter()
    r = subprocess.run(
        [sys.executable, str(ROOT / "scripts" / "lint_invariants.py"), str(ROOT)],
        capture_output=True, text=True, timeout=60,
    )
    wall = time.perf_counter() - t0
    assert r.returncode == 0, f"\n{r.stdout}\n{r.stderr}"
    assert wall < 10.0, f"lint took {wall:.1f}s — budget is 10s"
    baseline = json.loads((ROOT / "kakveda_tpu/analysis/baseline.json").read_text())
    assert baseline == [], "the baseline must stay empty — fix, don't grandfather"


def test_json_output_and_exit_codes():
    r = subprocess.run(
        [sys.executable, str(ROOT / "scripts" / "lint_invariants.py"),
         str(ROOT), "--json"],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 0, r.stdout
    out = json.loads(r.stdout)
    assert out["findings"] == []
    assert len(out["rules"]) >= 6
    r = subprocess.run(
        [sys.executable, str(ROOT / "scripts" / "lint_invariants.py"),
         str(ROOT), "--rule", "no-such-rule"],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 2


# ---------------------------------------------------------------------------
# forward-flag-parity
# ---------------------------------------------------------------------------

_PARITY_COMMON = {
    "kakveda_tpu/models/serving.py": """
        def _forward_wide(params, cfg, tokens):
            x = 1 if cfg.scale_embed else 0
            return x + cfg.final_softcap
    """,
    "kakveda_tpu/models/pipeline.py": """
        def pp_forward(stacked, cfg, tokens):
            x = 1 if cfg.scale_embed else 0
            return x + cfg.final_softcap
    """,
}

_PARITY_LLAMA_GOOD = """
    class LlamaConfig:
        scale_embed: bool = False
        final_softcap: float = 0.0

    def forward(params, cfg, tokens):
        x = 1 if cfg.scale_embed else 0
        return x + cfg.final_softcap

    def decode_step(params, cfg, tokens, cache):
        x = 1 if cfg.scale_embed else 0
        return x + cfg.final_softcap
"""


def test_forward_flag_parity_bad(tmp_path):
    # decode_step forgets final_softcap — the exact "added a family flag
    # to three of the four forward paths" failure mode. The good twin's
    # decode_step is its LAST function, so one targeted replace breaks it
    # without touching forward.
    bad_llama = textwrap.dedent(_PARITY_LLAMA_GOOD)
    assert bad_llama.rstrip().endswith("return x + cfg.final_softcap")
    bad_llama = bad_llama.rstrip()[: -len(" + cfg.final_softcap")] + "\n"
    root = _tree(tmp_path, {
        **_PARITY_COMMON,
        "kakveda_tpu/models/llama.py": bad_llama,
    })
    fs = _findings(root, "forward-flag-parity")
    assert len(fs) == 1, [f.human() for f in fs]
    assert "decode_step" in fs[0].message and "final_softcap" in fs[0].message


def test_forward_flag_parity_good(tmp_path):
    root = _tree(tmp_path, {
        **_PARITY_COMMON,
        "kakveda_tpu/models/llama.py": _PARITY_LLAMA_GOOD,
    })
    assert _findings(root, "forward-flag-parity") == []


def test_forward_flag_parity_real_tree_mutation(tmp_path):
    """Acceptance criterion: deleting a flag read from one of the REAL
    four forward paths makes the lint fail."""
    files = ["llama.py", "serving.py", "pipeline.py", "attention.py", "moe.py"]
    for f in files:
        dst = tmp_path / "kakveda_tpu/models" / f
        dst.parent.mkdir(parents=True, exist_ok=True)
        dst.write_text((ROOT / "kakveda_tpu/models" / f).read_text())
    assert _findings(tmp_path, "forward-flag-parity") == []

    p = tmp_path / "kakveda_tpu/models/llama.py"
    src = p.read_text()
    start = src.index("def decode_step")
    seg = src[start:]
    assert seg.count("softcap=cfg.attn_softcap") == 1
    p.write_text(src[:start] + seg.replace("softcap=cfg.attn_softcap", "softcap=0.0"))
    fs = _findings(tmp_path, "forward-flag-parity")
    assert any("decode_step" in f.message and "attn_softcap" in f.message for f in fs), [
        f.human() for f in fs
    ]


# ---------------------------------------------------------------------------
# single-writer
# ---------------------------------------------------------------------------

_SW_GOOD = """
    class BrownoutController:
        def __init__(self):
            self._step = 0
        def _set_brownout_state(self, new_step, pressure):
            self._step = new_step
        def note_pressure(self, pressure):
            if pressure > 0.9:
                self._set_brownout_state(self._step + 1, pressure)
"""


def test_single_writer_bad(tmp_path):
    bad = textwrap.dedent(_SW_GOOD) + (
        "    def force(self):\n"
        "        self._step = 3\n"
    )
    root = _tree(tmp_path, {"kakveda_tpu/core/admission.py": bad})
    fs = _findings(root, "single-writer")
    assert len(fs) == 1, [f.human() for f in fs]
    assert "_step" in fs[0].message and "force" in fs[0].message


def test_single_writer_good(tmp_path):
    root = _tree(tmp_path, {"kakveda_tpu/core/admission.py": _SW_GOOD})
    assert _findings(root, "single-writer") == []


# ---------------------------------------------------------------------------
# stats-lock
# ---------------------------------------------------------------------------

_SL_BAD = """
    import threading

    class ContinuousBatcher:
        def __init__(self):
            self.stats_lock = threading.RLock()
            self.spec_stats = {"chunks": 0}
        def process_chunk(self):
            self.spec_stats["chunks"] += 1
"""

_SL_GOOD = """
    import threading

    class ContinuousBatcher:
        def __init__(self):
            self.stats_lock = threading.RLock()
            self.spec_stats = {"chunks": 0}
        def process_chunk(self):
            with self.stats_lock:
                s = self.spec_stats
                s["chunks"] += 1
"""


def test_stats_lock_bad(tmp_path):
    root = _tree(tmp_path, {"kakveda_tpu/models/serving.py": _SL_BAD})
    fs = _findings(root, "stats-lock")
    assert len(fs) == 1, [f.human() for f in fs]
    assert "process_chunk" in fs[0].message


def test_stats_lock_good_including_alias(tmp_path):
    root = _tree(tmp_path, {"kakveda_tpu/models/serving.py": _SL_GOOD})
    assert _findings(root, "stats-lock") == []


def test_stats_lock_alias_mutation_outside_lock(tmp_path):
    """An alias taken under the lock but mutated outside it is still a
    violation — the lexical block is the contract."""
    src = _SL_GOOD.replace(
        "            with self.stats_lock:\n"
        "                s = self.spec_stats\n"
        "                s[\"chunks\"] += 1",
        "            with self.stats_lock:\n"
        "                s = self.spec_stats\n"
        "            s[\"chunks\"] += 1",
    )
    root = _tree(tmp_path, {"kakveda_tpu/models/serving.py": src})
    fs = _findings(root, "stats-lock")
    assert len(fs) == 1, [f.human() for f in fs]


def test_stats_lock_external_read(tmp_path):
    root = _tree(tmp_path, {
        "kakveda_tpu/models/serving.py": _SL_GOOD,
        "kakveda_tpu/service/panel.py": """
            def panel(engine):
                return engine.cb.spec_stats
        """,
    })
    fs = _findings(root, "stats-lock")
    assert len(fs) == 1 and fs[0].file == "kakveda_tpu/service/panel.py"


def test_stats_lock_real_tree_guard_deletion(tmp_path):
    """Acceptance criterion: deleting a `with stats_lock` guard from the
    REAL serving module makes the lint fail."""
    dst = tmp_path / "kakveda_tpu/models/serving.py"
    dst.parent.mkdir(parents=True, exist_ok=True)
    src = (ROOT / "kakveda_tpu/models/serving.py").read_text()
    dst.write_text(src)
    assert _findings(tmp_path, "stats-lock") == []

    guarded = (
        'with self.stats_lock:\n            self.prefix_stats["registered"] += 1'
    )
    assert guarded in src
    dst.write_text(src.replace(
        guarded, 'self.prefix_stats["registered"] += 1', 1
    ))
    fs = _findings(tmp_path, "stats-lock")
    assert len(fs) >= 1, "deleting a stats_lock guard must fail the lint"


# ---------------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------------


def test_host_sync_bad(tmp_path):
    root = _tree(tmp_path, {
        "kakveda_tpu/models/m.py": """
            import jax
            import numpy as np

            @jax.jit
            def step(x):
                return np.asarray(x)
        """,
    })
    fs = _findings(root, "host-sync")
    assert len(fs) == 1 and "np.asarray" in fs[0].message


def test_host_sync_good(tmp_path):
    root = _tree(tmp_path, {
        "kakveda_tpu/models/m.py": """
            import jax
            import jax.numpy as jnp
            import numpy as np

            @jax.jit
            def step(x):
                return jnp.asarray(x) + 1

            def host_side(x):
                return np.asarray(x)  # fine: not a traced body
        """,
    })
    assert _findings(root, "host-sync") == []


def test_host_sync_scan_body_and_item(tmp_path):
    root = _tree(tmp_path, {
        "kakveda_tpu/ops/o.py": """
            import jax

            def outer(xs):
                def body(carry, x):
                    return carry + x.item(), None
                return jax.lax.scan(body, 0, xs)
        """,
    })
    fs = _findings(root, "host-sync")
    assert len(fs) == 1 and ".item()" in fs[0].message


def test_host_sync_mirror_copy(tmp_path):
    bad = """
        import jax.numpy as jnp

        class CB:
            def step(self):
                return jnp.asarray(self._kv_np)
    """
    root = _tree(tmp_path, {"kakveda_tpu/models/serving.py": bad})
    fs = _findings(root, "host-sync")
    assert len(fs) == 1 and ".copy()" in fs[0].message
    root2 = _tree(tmp_path / "g", {
        "kakveda_tpu/models/serving.py": bad.replace("self._kv_np", "self._kv_np.copy()"),
    })
    assert _findings(root2, "host-sync") == []


# ---------------------------------------------------------------------------
# typed-errors
# ---------------------------------------------------------------------------

_TE_BAD = """
    def handler(eng):
        try:
            return eng.submit([1, 2, 3])
        except Exception:
            return None
"""


def test_typed_errors_bad(tmp_path):
    root = _tree(tmp_path, {"kakveda_tpu/service/h.py": _TE_BAD})
    fs = _findings(root, "typed-errors")
    assert len(fs) == 1, [f.human() for f in fs]


def test_typed_errors_good_variants(tmp_path):
    root = _tree(tmp_path, {
        # Typed errors handled first: the broad tail is fine.
        "kakveda_tpu/service/a.py": """
            def handler(eng):
                try:
                    return eng.submit([1])
                except OverloadError:
                    raise
                except Exception:
                    return None
        """,
        # Propagating the original exception keeps it typed.
        "kakveda_tpu/service/b.py": """
            def handler(eng, fut):
                try:
                    return eng.submit([1])
                except Exception as e:
                    fut.set_exception(e)
        """,
        # No typed-error source in the try: broad catch is fine.
        "kakveda_tpu/service/c.py": """
            async def handler(request):
                try:
                    return await request.json()
                except Exception:
                    return {}
        """,
    })
    assert _findings(root, "typed-errors") == []


# ---------------------------------------------------------------------------
# fault-site-once
# ---------------------------------------------------------------------------


def test_fault_site_once_bad(tmp_path):
    root = _tree(tmp_path, {
        "kakveda_tpu/x.py": """
            from kakveda_tpu.core import faults as _faults

            def hot_path():
                _faults.site("engine.hotloop").fire()
        """,
    })
    fs = _findings(root, "fault-site-once")
    assert len(fs) == 1 and "hot_path" in fs[0].message


def test_fault_site_once_good(tmp_path):
    root = _tree(tmp_path, {
        "kakveda_tpu/x.py": """
            from kakveda_tpu.core import faults as _faults

            _MODULE_SITE = _faults.site("engine.import_time")

            class C:
                def __init__(self):
                    self._site = _faults.site("engine.ctor")
                def hot(self):
                    self._site.fire()
        """,
    })
    assert _findings(root, "fault-site-once") == []


# ---------------------------------------------------------------------------
# fault-site-catalog + knob-docs (check_knobs, as rules)
# ---------------------------------------------------------------------------


def test_fault_site_catalog_rule(tmp_path):
    root = _tree(tmp_path, {
        "kakveda_tpu/x.py": """
            from kakveda_tpu.core import faults as _faults
            _A = _faults.site("engine.newsite")
            _B = _faults.site("gfkb.cataloged")
        """,
        "docs/robustness.md": "| `gfkb.cataloged` | somewhere | documented |\n",
    })
    fs = _findings(root, "fault-site-catalog")
    assert len(fs) == 1 and "engine.newsite" in fs[0].message


def test_knob_docs_rule(tmp_path):
    root = _tree(tmp_path, {
        "kakveda_tpu/x.py": """
            import os
            os.environ.get("KAKVEDA_TOTALLY_NEW_KNOB")
            os.environ.get("KAKVEDA_DOCUMENTED_KNOB")
        """,
        "docs/a.md": "`KAKVEDA_DOCUMENTED_KNOB` does x; `KAKVEDA_GONE_KNOB` is dead\n",
    })
    fs = _findings(root, "knob-docs")
    msgs = " | ".join(f.message for f in fs)
    assert len(fs) == 2, [f.human() for f in fs]
    assert "KAKVEDA_TOTALLY_NEW_KNOB" in msgs and "KAKVEDA_GONE_KNOB" in msgs


# ---------------------------------------------------------------------------
# framework: pragmas, baseline, syntax errors
# ---------------------------------------------------------------------------


def test_suppression_pragma(tmp_path):
    src = _SL_BAD.replace(
        'self.spec_stats["chunks"] += 1',
        'self.spec_stats["chunks"] += 1  # kakveda: allow[stats-lock]',
    )
    root = _tree(tmp_path, {"kakveda_tpu/models/serving.py": src})
    res = run_lint(root, rule_ids=["stats-lock"])
    assert res.findings == [] and len(res.suppressed) == 1


def test_pragma_on_preceding_line(tmp_path):
    src = _SL_BAD.replace(
        '            self.spec_stats["chunks"] += 1',
        '            # kakveda: allow[stats-lock]\n'
        '            self.spec_stats["chunks"] += 1',
    )
    root = _tree(tmp_path, {"kakveda_tpu/models/serving.py": src})
    res = run_lint(root, rule_ids=["stats-lock"])
    assert res.findings == [] and len(res.suppressed) == 1


def test_baseline_grandfathers_but_does_not_hide_new(tmp_path):
    root = _tree(tmp_path, {"kakveda_tpu/models/serving.py": _SL_BAD})
    res = run_lint(root, rule_ids=["stats-lock"])
    assert len(res.findings) == 1
    bl = root / "kakveda_tpu/analysis/baseline.json"
    bl.parent.mkdir(parents=True, exist_ok=True)
    bl.write_text(json.dumps([res.findings[0].baseline_key]))
    res = run_lint(root, rule_ids=["stats-lock"])
    assert res.findings == [] and len(res.baselined) == 1


def test_unparseable_file_is_a_finding(tmp_path):
    root = _tree(tmp_path, {"kakveda_tpu/broken.py": "def f(:\n"})
    res = run_lint(root, rule_ids=["stats-lock"])
    assert len(res.findings) == 1 and res.findings[0].rule == "syntax"


def test_cli_exit_1_on_findings(tmp_path):
    root = _tree(tmp_path, {"kakveda_tpu/models/serving.py": _SL_BAD})
    r = subprocess.run(
        [sys.executable, str(ROOT / "scripts" / "lint_invariants.py"), str(root)],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 1
    assert "stats-lock" in r.stdout
