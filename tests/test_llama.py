"""Llama model tests: shapes, ring-attention equivalence, cached decode
consistency, training convergence, sharded train step on dp×cp×tp mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kakveda_tpu.models.llama import (
    LlamaConfig,
    _repeat_kv,
    causal_attention,
    decode_step,
    forward,
    init_cache,
    init_params,
    param_specs,
)
from kakveda_tpu.models.tokenizer import ByteTokenizer
from kakveda_tpu.parallel.mesh import create_mesh

CFG = LlamaConfig(
    vocab_size=264,
    d_model=64,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    d_ff=128,
    max_seq_len=128,
    dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


def test_forward_shapes(params):
    tokens = jnp.ones((2, 16), jnp.int32)
    logits = forward(params, CFG, tokens)
    assert logits.shape == (2, 16, CFG.vocab_size)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()


def test_causality(params):
    """Changing a future token must not change past logits."""
    rng = np.random.default_rng(0)
    t1 = rng.integers(3, 259, size=(1, 16)).astype(np.int32)
    t2 = t1.copy()
    t2[0, -1] = (t2[0, -1] - 3 + 1) % 256 + 3
    l1 = forward(params, CFG, jnp.asarray(t1))
    l2 = forward(params, CFG, jnp.asarray(t2))
    np.testing.assert_allclose(np.asarray(l1[0, :-1]), np.asarray(l2[0, :-1]), atol=1e-5)
    assert not np.allclose(np.asarray(l1[0, -1]), np.asarray(l2[0, -1]))


def test_ring_attention_matches_dense(params):
    """Ring attention over a cp>1 mesh must reproduce single-device attention."""
    mesh = create_mesh("dp:1,cp:4,tp:2")
    tokens = jnp.asarray(np.random.default_rng(1).integers(3, 259, size=(2, 32)), jnp.int32)
    dense = forward(params, CFG, tokens)
    ring = forward(params, CFG, tokens, mesh=mesh, cp_axis="cp")
    np.testing.assert_allclose(np.asarray(dense), np.asarray(ring), atol=2e-3, rtol=1e-3)


def test_ring_attention_softcap_and_alt_window(params):
    """Gemma-2-style attn softcapping + alternating per-layer windows must
    survive the ring (cp) path identically to the dense path — the softcap
    is applied inside every ring sub-block before masking."""
    import dataclasses

    cfg2 = dataclasses.replace(CFG, attn_softcap=5.0, sliding_window=8, alt_window=True)
    mesh = create_mesh("dp:1,cp:4,tp:2")
    tokens = jnp.asarray(np.random.default_rng(3).integers(3, 259, size=(2, 32)), jnp.int32)
    dense = forward(params, cfg2, tokens)
    # the deltas must actually change the logits vs the plain config
    assert np.abs(np.asarray(dense) - np.asarray(forward(params, CFG, tokens))).max() > 1e-3
    ring = forward(params, cfg2, tokens, mesh=mesh, cp_axis="cp")
    np.testing.assert_allclose(np.asarray(dense), np.asarray(ring), atol=2e-3, rtol=1e-3)


def test_decode_matches_forward(params):
    """Prefill+incremental decode logits must match the full forward pass."""
    rng = np.random.default_rng(2)
    ids = rng.integers(3, 259, size=(1, 12)).astype(np.int32)
    full = np.asarray(forward(params, CFG, jnp.asarray(ids)))

    cache = init_cache(CFG, batch=1, max_len=32)
    # prefill first 8, then 4 single-token steps
    l1, cache = decode_step(params, CFG, jnp.asarray(ids[:, :8]), cache)
    got = [np.asarray(l1)]
    for i in range(8, 12):
        li, cache = decode_step(params, CFG, jnp.asarray(ids[:, i : i + 1]), cache)
        got.append(np.asarray(li))
    got = np.concatenate(got, axis=1)
    np.testing.assert_allclose(got, full, atol=1e-3, rtol=1e-3)


def test_generate_deterministic():
    from kakveda_tpu.models.generate import LlamaRuntime

    rt = LlamaRuntime(cfg=CFG, seed=0)
    r1 = rt.generate("hello", max_tokens=8)
    r2 = rt.generate("hello", max_tokens=8)
    assert r1.text == r2.text
    assert r1.meta["provider"] == "tpu"
    assert r1.meta["tokens_generated"] <= 8


def test_train_step_reduces_loss():
    from kakveda_tpu.models.train import make_train_step

    cfg = CFG
    params = init_params(jax.random.PRNGKey(1), cfg)
    step, opt = make_train_step(cfg)
    opt_state = opt.init(params)
    tokens = jnp.asarray(
        np.tile(np.arange(3, 19, dtype=np.int32), (4, 1))  # a memorizable sequence
    )
    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses


def test_sharded_train_step_dp_cp_tp():
    """Full training step jitted over a 2×2×2 mesh: tp-sharded params,
    dp×cp-sharded batch, ring attention across cp."""
    from kakveda_tpu.models.train import make_sharded_train_step

    mesh = create_mesh("dp:2,cp:2,tp:2")
    step, init_state = make_sharded_train_step(CFG, mesh)
    params, opt_state = init_state(jax.random.PRNGKey(0))

    # param sharding actually applied
    wq = params["layers"][0]["wq"]
    assert wq.sharding.spec == param_specs(CFG)["layers"][0]["wq"]

    tokens = jnp.asarray(np.random.default_rng(3).integers(3, 259, size=(4, 32)), jnp.int32)
    params, opt_state, loss = step(params, opt_state, tokens)
    assert np.isfinite(float(loss))
    params, opt_state, loss2 = step(params, opt_state, tokens)
    assert float(loss2) < float(loss)


def test_sharded_loss_matches_unsharded():
    """The dp×cp×tp-sharded loss must equal the single-device loss."""
    from kakveda_tpu.models.train import lm_loss, make_sharded_train_step

    mesh = create_mesh("dp:2,cp:2,tp:2")
    params = init_params(jax.random.PRNGKey(0), CFG)
    tokens = jnp.asarray(np.random.default_rng(4).integers(3, 259, size=(4, 32)), jnp.int32)
    base = float(lm_loss(params, CFG, tokens))

    from kakveda_tpu.models.train import shard_params

    sp = shard_params(params, CFG, mesh)
    sharded = float(lm_loss(sp, CFG, tokens, mesh, "cp"))
    assert abs(base - sharded) / abs(base) < 1e-3


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    s = "Héllo, wörld! 失敗 🙂"
    ids = tok.encode(s, bos=True, eos=True)
    assert ids[0] == tok.BOS and ids[-1] == tok.EOS
    assert tok.decode(ids) == s
    assert max(ids) < tok.vocab_size


def test_generate_top_p_sampling():
    import jax

    from kakveda_tpu.models.generate import generate_tokens
    from kakveda_tpu.models.llama import init_params

    params = init_params(jax.random.PRNGKey(0), CFG)
    ids = generate_tokens(
        params, CFG, [5, 6, 7], max_new_tokens=8, temperature=0.8, top_p=0.9,
        rng=jax.random.PRNGKey(1),
    )
    assert 0 < len(ids) <= 8
    assert all(0 <= t < CFG.vocab_size for t in ids)
    # top_p=tiny keeps only the argmax nucleus → matches greedy
    greedy = generate_tokens(params, CFG, [5, 6, 7], max_new_tokens=8, temperature=0.0)
    nucleus = generate_tokens(
        params, CFG, [5, 6, 7], max_new_tokens=8, temperature=0.5, top_p=1e-6,
        rng=jax.random.PRNGKey(2),
    )
    assert nucleus == greedy


def test_fit_and_checkpoint_roundtrip(tmp_path):
    from kakveda_tpu.models.generate import LlamaRuntime
    from kakveda_tpu.models.train import fit

    ckpt = str(tmp_path / "ckpt")
    params, losses = fit(
        CFG, "the platform remembers failures. " * 40,
        steps=12, batch=2, seq_len=64, checkpoint_path=ckpt, log_every=0,
        log_fn=lambda s: None,
    )
    assert losses[-1] < losses[0]

    rt = LlamaRuntime(cfg=CFG, params=params)
    expected = rt.generate("the platform", max_tokens=8).text
    fresh = LlamaRuntime(cfg=CFG, seed=999)  # different init...
    fresh.load_checkpoint(ckpt)              # ...restored from disk
    assert fresh.generate("the platform", max_tokens=8).text == expected


def test_batched_generation_matches_single():
    """Left-padded batching with position offsets + KV masks is exact: each
    sequence's greedy output equals its solo generate_tokens output."""
    import jax

    from kakveda_tpu.models.generate import generate_tokens, generate_tokens_batch
    from kakveda_tpu.models.llama import init_params

    params = init_params(jax.random.PRNGKey(0), CFG)
    prompts = [[5, 6, 7], [10, 11, 12, 13, 14, 15, 16], [42]]
    solo = [
        generate_tokens(params, CFG, p, max_new_tokens=8, max_len=128) for p in prompts
    ]
    batched = generate_tokens_batch(params, CFG, prompts, max_new_tokens=8)
    assert batched == solo


def test_fused_generation_matches_step_loop():
    """The one-compiled-program decode (lax.scan over decode_step) must emit
    exactly what the per-step loop emits under greedy sampling."""
    import jax

    from kakveda_tpu.models.generate import generate_tokens_batch, generate_tokens_fused
    from kakveda_tpu.models.llama import init_params

    params = init_params(jax.random.PRNGKey(0), CFG)
    prompts = [[5, 6, 7], [10, 11, 12, 13, 14, 15, 16], [42]]
    stepped = generate_tokens_batch(params, CFG, prompts, max_new_tokens=8)
    fused = generate_tokens_fused(params, CFG, prompts, max_new_tokens=8)
    assert fused == stepped

    # EOS truncation: force an eos_id that the greedy path emits and check
    # the fused output stops there like the step loop does.
    eos = stepped[0][2] if len(stepped[0]) > 2 else None
    if eos is not None:
        f = generate_tokens_fused(params, CFG, prompts, max_new_tokens=8, eos_id=eos)
        s = generate_tokens_batch(params, CFG, prompts, max_new_tokens=8, eos_id=eos)
        assert f == s


def test_runtime_generate_batch():
    from kakveda_tpu.models.generate import LlamaRuntime

    rt = LlamaRuntime(cfg=CFG, seed=0)
    solo = [rt.generate(p, max_tokens=6).text for p in ("hello", "a longer prompt here")]
    batch = rt.generate_batch(["hello", "a longer prompt here"], max_tokens=6)
    assert [r.text for r in batch] == solo
    assert batch[0].meta["batched"] == 2


def test_decode_session_chunked_parity():
    """Chunked decode (DecodeSession) must emit exactly the fused whole-
    generation tokens — greedy, across uneven chunk boundaries — and honor
    the cache window."""
    import numpy as np

    from kakveda_tpu.models.generate import DecodeSession, generate_tokens_fused
    from kakveda_tpu.models.llama import init_params

    params = init_params(jax.random.PRNGKey(0), CFG)
    prompts = [[5, 6, 7], [10, 11, 12, 13, 14, 15, 16], [42]]
    fused = generate_tokens_fused(params, CFG, prompts, max_new_tokens=12)

    sess = DecodeSession(params, CFG, prompts, chunk_steps=5, max_len=64)
    chunks = []
    while (c := sess.step_chunk()) is not None and sum(x.shape[1] for x in chunks) < 12:
        chunks.append(c)
    toks = np.concatenate(chunks, axis=1)[:, :12]
    for i in range(len(prompts)):
        assert toks[i].tolist() == fused[i][:12]

    # Window exhaustion: session stops at max_len-1 total positions.
    small = DecodeSession(params, CFG, [[5, 6, 7]], chunk_steps=64, max_len=16)
    out = small.step_chunk()
    assert out is not None and out.shape[1] == 16 - 1 - 3
    assert small.step_chunk() is None


def test_tp_sharded_generation_matches_single():
    """Fused generation with Megatron-TP-sharded params on a tp:2 mesh must
    emit exactly the single-device greedy tokens (XLA inserts the tp
    collectives from the param shardings; batch stays replicated)."""
    from kakveda_tpu.models.generate import generate_tokens_fused
    from kakveda_tpu.models.hf_convert import shard_params
    from kakveda_tpu.models.llama import init_params

    params = init_params(jax.random.PRNGKey(0), CFG)
    prompts = [[5, 6, 7], [10, 11, 12, 13]]
    single = generate_tokens_fused(params, CFG, prompts, max_new_tokens=8)

    mesh = create_mesh("dp:1,tp:2")
    sharded = shard_params(params, CFG, mesh)
    wq = sharded["layers"][0]["wq"]
    assert wq.sharding.spec == param_specs(CFG)["layers"][0]["wq"]
    tp_out = generate_tokens_fused(sharded, CFG, prompts, max_new_tokens=8)
    assert tp_out == single


def test_ring_attention_key_blocking_matches_dense():
    """Sub-blocked ring hops (key_block < S_local) must still reproduce
    dense attention — the second-level online-softmax accumulation is
    exact, not approximate."""
    from functools import partial

    from kakveda_tpu.models.llama import ring_attention_local

    mesh = create_mesh("dp:1,cp:4,tp:1")
    rng = np.random.default_rng(7)
    b, s, h, kvh, d = 2, 32, 4, 2, 16
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, kvh, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, kvh, d)), jnp.float32)

    from jax.sharding import PartitionSpec as P

    from kakveda_tpu.parallel.mesh import shard_map

    def run(key_block):
        spec = P("dp", "cp", None, None)
        return shard_map(
            partial(ring_attention_local, axis_name="cp", n_chunks=4, key_block=key_block),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False,
        )(q, k, v)

    dense = np.asarray(causal_attention(q, _repeat_kv(k, 2), _repeat_kv(v, 2)))
    blocked = np.asarray(run(key_block=4))  # S_local=8 → 2 sub-blocks/hop
    unblocked = np.asarray(run(key_block=2048))
    np.testing.assert_allclose(blocked, dense, atol=2e-5, rtol=1e-4)
    np.testing.assert_allclose(blocked, unblocked, atol=1e-6)
