"""The metrics plane: registry exposition format, concurrent-scrape
safety, serving-engine lifecycle instrumentation, flight-recorder
round-trips, and the /metrics + /flightrecorder HTTP endpoints."""

import asyncio
import json
import threading

import jax
import pytest
from aiohttp.test_utils import TestClient, TestServer

from kakveda_tpu.core import metrics as m


# ---------------------------------------------------------------------------
# exposition format
# ---------------------------------------------------------------------------


def test_counter_gauge_exposition_help_type_and_escaping():
    reg = m.MetricsRegistry(preregister=False)
    c = reg.counter("demo_total", "a counter", ("who",))
    c.labels(who='he said "hi"\\here\nline').inc(3)
    g = reg.gauge("depth", "a gauge")
    g.set(2.5)
    text = reg.render()
    lines = text.splitlines()
    assert "# HELP demo_total a counter" in lines
    assert "# TYPE demo_total counter" in lines
    assert "# TYPE depth gauge" in lines
    # label escaping: backslash, double quote, and newline all escape
    assert 'demo_total{who="he said \\"hi\\"\\\\here\\nline"} 3' in lines
    assert "depth 2.5" in lines
    # HELP precedes TYPE precedes samples, per family
    hi, ti = lines.index("# HELP demo_total a counter"), lines.index("# TYPE demo_total counter")
    si = next(i for i, ln in enumerate(lines) if ln.startswith("demo_total{"))
    assert hi < ti < si


def test_histogram_buckets_monotone_inf_and_sum():
    reg = m.MetricsRegistry(preregister=False)
    h = reg.histogram("lat_seconds", "latency", (), buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0, 0.05):
        h.observe(v)
    lines = reg.render().splitlines()
    buckets = [ln for ln in lines if ln.startswith("lat_seconds_bucket")]
    # le values render in ascending order ending at +Inf
    assert [ln.split("le=")[1].split("}")[0] for ln in buckets] == [
        '"0.01"', '"0.1"', '"1"', '"+Inf"',
    ]
    counts = [int(ln.rsplit(" ", 1)[1]) for ln in buckets]
    assert counts == sorted(counts), "cumulative bucket counts must be monotone"
    assert counts[-1] == 5  # +Inf == observation count
    assert "lat_seconds_count 5" in lines
    sum_line = next(ln for ln in lines if ln.startswith("lat_seconds_sum"))
    assert abs(float(sum_line.split(" ")[1]) - 5.605) < 1e-9


def test_registry_get_or_create_and_shape_conflicts():
    reg = m.MetricsRegistry(preregister=False)
    a = reg.counter("x_total", "x", ("l",))
    assert reg.counter("x_total", "ignored", ("l",)) is a
    with pytest.raises(ValueError):
        reg.gauge("x_total", "x", ("l",))  # type conflict
    with pytest.raises(ValueError):
        reg.counter("x_total", "x", ("other",))  # labelname conflict
    with pytest.raises(ValueError):
        a.labels(wrong="v")  # label key mismatch


def test_concurrent_updates_while_scraping():
    """Scrape safety: renders interleaved with updates never raise and
    never lose counts."""
    reg = m.MetricsRegistry(preregister=False)
    c = reg.counter("hits_total", "h", ("t",))
    h = reg.histogram("obs_seconds", "o", (), buckets=(0.5,))
    N, T = 2000, 4
    children = [c.labels(t=str(i)) for i in range(T)]

    def work(i):
        for _ in range(N):
            children[i].inc()
            h.observe(0.1)

    threads = [threading.Thread(target=work, args=(i,)) for i in range(T)]
    for t in threads:
        t.start()
    # Scrape while updates are (likely) in flight — and a fixed number of
    # times regardless, so the assertion never depends on thread timing.
    for _ in range(50):
        text = reg.render()
        assert "hits_total" in text
    for t in threads:
        t.join()
    final = reg.render().splitlines()
    vals = [int(ln.rsplit(" ", 1)[1]) for ln in final if ln.startswith("hits_total{")]
    assert sum(vals) == N * T
    assert f"obs_seconds_count {N * T}" in final


def test_preregistered_catalog_is_self_describing():
    """A bare scrape of the default registry already names the serving
    TTFT / tokens-per-second / gate-state families (HELP/TYPE lines)."""
    text = m.get_registry().render()
    for fam in (
        "kakveda_serving_ttft_seconds",
        "kakveda_serving_tokens_per_second",
        "kakveda_serving_spec_gate_state",
        "kakveda_serving_queue_wait_seconds",
    ):
        assert f"# TYPE {fam} " in text, fam


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flight_recorder_ring_bound_and_json_roundtrip():
    fr = m.FlightRecorder("test/ring", capacity=4)
    for i in range(9):
        fr.record("request", request_id=i, wall_ms=1.5 * i)
    events = fr.dump()
    assert [e["request_id"] for e in events] == [5, 6, 7, 8]
    # round-trips through JSON unchanged
    assert json.loads(json.dumps(events)) == events
    assert json.loads(fr.dump_json())["name"] == "test/ring"
    # the global dump enumerates this recorder by name
    names = [r["name"] for r in m.dump_recorders()]
    assert "test/ring" in names


def test_flight_recorder_capacity_zero_disables():
    fr = m.FlightRecorder("test/off", capacity=0)
    fr.record("request", request_id=1)
    assert fr.dump() == []


# ---------------------------------------------------------------------------
# serving-engine lifecycle instrumentation
# ---------------------------------------------------------------------------

CFG = None


def _tiny_cfg():
    global CFG
    if CFG is None:
        import jax.numpy as jnp

        from kakveda_tpu.models.llama import LlamaConfig

        CFG = LlamaConfig(
            vocab_size=264, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
            d_ff=128, max_seq_len=128, dtype=jnp.float32,
        )
    return CFG


def test_serving_engine_lifecycle_metrics_and_recorder():
    from kakveda_tpu.models.llama import init_params
    from kakveda_tpu.models.serving import ServingEngine

    cfg = _tiny_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    eng = ServingEngine(
        params, cfg, batch_slots=2, max_len=64, chunk_steps=4,
        name="metrics-test",
    )
    try:
        prompts = [[5, 6, 7], [9, 8], [41, 42, 43]]
        futs = [eng.submit(p, max_new_tokens=6) for p in prompts]
        outs = [f.result(timeout=120) for f in futs]
        assert all(len(o) > 0 for o in outs)

        # stats() is a snapshot: mutating it must not touch engine state
        s = eng.stats()
        assert s["completed"] == 3
        s["spec"]["k_trace"].append(999)
        assert 999 not in eng.cb.spec_stats["k_trace"]

        # lifecycle histograms landed under this engine's label
        text = m.get_registry().render()
        assert 'kakveda_serving_ttft_seconds_count{engine="metrics-test"} 3' in text
        assert 'kakveda_serving_request_seconds_count{engine="metrics-test"} 3' in text
        assert 'kakveda_serving_tokens_per_second_count{engine="metrics-test"} 3' in text
        assert (
            'kakveda_serving_requests_total{engine="metrics-test",outcome="completed"} 3'
            in text
        )
        # gate-state gauge: spec disabled pool advertises state=disabled
        assert (
            'kakveda_serving_spec_gate_state{engine="metrics-test",state="disabled"} 1'
            in text
        )

        # the flight recorder holds one timeline per request with the
        # correlating fields
        reqs = [e for e in eng.recorder.dump() if e["kind"] == "request"]
        assert len(reqs) == 3
        for e in reqs:
            for key in ("request_id", "queue_wait_ms", "ttft_ms", "wall_ms",
                        "tokens", "tokens_per_s"):
                assert key in e, key
            assert e["tokens"] > 0
        # the engine timeline also rides the caller's Future
        assert futs[0].timeline["tokens"] == len(outs[0])
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# HTTP endpoints
# ---------------------------------------------------------------------------


def _get_many(app, paths):
    """One event loop for all requests — an aiohttp app binds to the loop
    it first serves on."""

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        out = []
        try:
            for path in paths:
                r = await client.get(path)
                out.append((r.status, r.headers.get("Content-Type", ""), await r.read()))
        finally:
            await client.close()
        return out

    return asyncio.run(go())


def test_service_metrics_and_flightrecorder_endpoints(tmp_path):
    from kakveda_tpu.platform import Platform
    from kakveda_tpu.service.app import make_app

    plat = Platform(data_dir=tmp_path / "data", capacity=256, dim=1024)
    app = make_app(plat)

    (status, ctype, body), (fstatus, _, fbody) = _get_many(
        app, ["/metrics", "/flightrecorder"]
    )
    assert status == 200
    assert ctype.startswith("text/plain")
    text = body.decode()
    assert "# TYPE kakveda_serving_ttft_seconds histogram" in text
    assert "# TYPE kakveda_serving_tokens_per_second histogram" in text
    assert "# TYPE kakveda_serving_spec_gate_state gauge" in text
    assert "# TYPE kakveda_ingest_traces_total counter" in text

    assert fstatus == 200
    payload = json.loads(fbody)
    assert isinstance(payload["recorders"], list)


def test_dashboard_mounts_metrics_routes(tmp_path):
    from kakveda_tpu.dashboard.app import make_dashboard_app
    from kakveda_tpu.platform import Platform

    plat = Platform(data_dir=tmp_path / "data", capacity=256, dim=1024)
    app = make_dashboard_app(platform=plat, db_path=tmp_path / "dash.db")
    (mstatus, _, mbody), (fstatus, _, fbody) = _get_many(
        app, ["/metrics", "/flightrecorder"]
    )
    assert mstatus == 200 and b"kakveda_serving_ttft_seconds" in mbody
    assert fstatus == 200 and b"recorders" in fbody


def test_ingest_traffic_lands_on_metrics_plane(tmp_path):
    """POST /ingest moves the pipeline counters the scrape reports."""
    from kakveda_tpu.platform import Platform
    from kakveda_tpu.service.app import make_app

    def series_value(name):
        snap = m.get_registry().snapshot()
        return sum(snap.get(name, {}).get("series", {}).values()) or 0

    before = series_value("kakveda_ingest_traces_total")
    plat = Platform(data_dir=tmp_path / "data", capacity=256, dim=1024)
    app = make_app(plat)

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post(
                "/ingest",
                json={
                    "trace": {
                        "trace_id": "t-metrics-1",
                        "ts": "2026-08-04T00:00:00Z",
                        "app_id": "metrics-app",
                        "agent_id": "a",
                        "prompt": "Cite sources",
                        "response": "References:\n[1] Fake (2020)",
                        "model": "stub",
                        "temperature": 0.1,
                        "tools": [],
                        "env": {},
                    }
                },
            )
            assert r.status == 200
        finally:
            await client.close()

    asyncio.run(go())
    assert series_value("kakveda_ingest_traces_total") >= before + 1
