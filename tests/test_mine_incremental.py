"""Incremental streaming pattern mining (ops/incremental.py + GFKB wiring).

Covers the contract stack bottom-up: the streaming ClusterState reproduces
the full-sweep partition exactly in the documented graph-equivalence regime
(every row's above-threshold degree ≤ k — property-tested over random
clustered corpora), the GFKB ingest path attaches rows with at most ONE
delta dispatch per batch (ZERO when a warn match already fetched the
neighbors), `KAKVEDA_MINE_INCREMENTAL=0` reproduces the full-sweep-only
behavior bit-for-bit, the cluster state rides the v4 snapshot
checksum-verified (corruption/faults degrade to one full re-mine, NEVER to
desynced labels), and `build_knn_edges` compiles O(log N) times over a
growing corpus thanks to pow2 padding.
"""

import numpy as np
import pytest

from kakveda_tpu.core import faults
from kakveda_tpu.core.schemas import Severity
from kakveda_tpu.index.gfkb import GFKB
from kakveda_tpu.ops.clustering import _KNN_K, _corpus_pad, cluster_embeddings
from kakveda_tpu.ops.incremental import (
    ClusterState,
    delta_topk_dense,
    unpack_topk,
)
from kakveda_tpu.pipeline.patterns import PatternDetector


@pytest.fixture(autouse=True)
def _disarm_faults():
    faults.disarm()
    yield
    faults.disarm()


# ---------------------------------------------------------------------------
# ClusterState vs the full-sweep oracle
# ---------------------------------------------------------------------------


def _clustered_corpus(rng, n_clusters, max_size, dim=64, jitter=0.04):
    """Random well-separated cluster centers, ≤ max_size members each —
    keeps every row's above-threshold degree under the cap so the
    graph-equivalence regime holds by construction (asserted by callers)."""
    rows = []
    for _ in range(n_clusters):
        c = rng.standard_normal(dim)
        c /= np.linalg.norm(c)
        for _ in range(int(rng.integers(1, max_size + 1))):
            w = c + jitter * rng.standard_normal(dim)
            rows.append(w / np.linalg.norm(w))
    order = rng.permutation(len(rows))
    return np.stack(rows).astype(np.float32)[order]


def _stream(vecs, threshold, k, batch=16):
    """The bench streaming arm in miniature: pad the corpus to its pow2
    bucket, stream batches through ONE delta top-k each, fold into a
    ClusterState, and materialize labels."""
    import jax.numpy as jnp

    n, dim = vecs.shape
    P = _corpus_pad(n)
    v_pad = jnp.asarray(
        np.concatenate([vecs, np.zeros((P - n, dim), np.float32)])
        if P != n
        else vecs
    )
    state = ClusterState(threshold=threshold, k=k)
    for s in range(0, n, batch):
        e = min(s + batch, n)
        q = np.zeros((batch, dim), np.float32)
        q[: e - s] = vecs[s:e]
        packed = delta_topk_dense(jnp.asarray(q), v_pad, e, k + 1)
        sims, idx = unpack_topk(packed, e - s)
        for r in range(e - s):
            state.add_row(s + r)
        for r in range(e - s):
            state.attach(s + r, idx[r], sims[r])
    return state


def test_streaming_parity_property_in_degree_cap_regime():
    """Whenever per-row above-threshold degree ≤ k, the incremental
    partition equals the full sweep's EXACTLY — the documented
    graph-equivalence regime, over randomized corpora and insertion
    orders (including rows that bridge earlier-separate groups)."""
    threshold, k = 0.6, 8
    checked = 0
    for seed in range(6):
        rng = np.random.default_rng(seed)
        vecs = _clustered_corpus(rng, n_clusters=7, max_size=6)
        sims = vecs @ vecs.T
        np.fill_diagonal(sims, 0.0)
        degree = (sims >= threshold).sum(axis=1)
        if degree.max() > k:
            continue  # outside the documented regime for this draw
        state = _stream(vecs, threshold, k, batch=int(rng.integers(3, 17)))
        oracle = cluster_embeddings(vecs, threshold=threshold)
        assert np.array_equal(state.labels(), oracle), f"seed {seed}"
        checked += 1
    assert checked >= 4, "property exercised on too few draws"


def test_streaming_merge_of_bridged_groups():
    """A late row similar to two so-far-separate groups merges them —
    unions are lazy (edge set → components at refresh), so the merge
    lands exactly like the full sweep's."""
    rng = np.random.default_rng(3)
    a = rng.standard_normal(64)
    a /= np.linalg.norm(a)
    b = rng.standard_normal(64)
    b /= np.linalg.norm(b)
    mid = (a + b) / np.linalg.norm(a + b)

    def jit(v):
        w = v + 0.03 * rng.standard_normal(64)
        return (w / np.linalg.norm(w)).astype(np.float32)

    vecs = np.stack([jit(a), jit(a), jit(b), jit(b), mid.astype(np.float32)])
    if float(min(mid @ vecs[0], mid @ vecs[2])) < 0.6:
        pytest.skip("bridge row did not clear the threshold for this draw")
    state = _stream(vecs, 0.6, k=8, batch=2)
    labels = state.labels()
    oracle = cluster_embeddings(vecs, threshold=0.6)
    assert np.array_equal(labels, oracle)
    assert len(np.unique(labels)) == 1  # the bridge merged everything


def test_cluster_state_rejects_slot_gaps():
    st = ClusterState(threshold=0.6, k=4)
    st.add_row(0)
    st.add_row(2)  # gap: slot 1 never arrived
    assert st.stale and "non-contiguous" in st.stale_reason


def test_pop_dirty_only_touched_clusters():
    """After a seed (full sweep just emitted everything) only clusters
    touched by later rows are re-emitted."""
    st = ClusterState(threshold=0.9, k=4)
    st.seed(np.zeros(3, np.int32), [("T", f"F-{i}", [f"a{i}"]) for i in range(3)])
    assert st.pop_dirty() == []  # nothing touched since the sweep
    st.add_row(3, "T", "F-3", ["a3"])
    st.attach(3, [0], [0.95])
    dirty = st.pop_dirty()
    assert [d["label"] for d in dirty] == [0]
    assert dirty[0]["n"] == 4 and "F-3" in dirty[0]["fids"]
    assert st.pop_dirty() == []  # drained


# ---------------------------------------------------------------------------
# GFKB wiring: ingest-time attachment, dispatch accounting, parity
# ---------------------------------------------------------------------------


def _mk(tmp_path, **kw):
    kw.setdefault("capacity", 256)
    kw.setdefault("dim", 1024)
    return GFKB(data_dir=tmp_path / "data", **kw)


_CORPUS = [
    # one canonical record shared by two apps (singleton cluster, 2 apps)
    ("HALLUCINATION_CITATION", "intent:citations_required | summarize the quarterly report", "app-A"),
    ("HALLUCINATION_CITATION", "intent:citations_required | summarize the quarterly report", "app-B"),
    # a family of near-identical timeout signatures across apps
    ("TIMEOUT", "timeout while calling payments api attempt 0", "app-A"),
    ("TIMEOUT", "timeout while calling payments api attempt 1", "app-B"),
    ("TIMEOUT", "timeout while calling payments api attempt 2", "app-C"),
    # an unrelated singleton
    ("SCHEMA", "totally different failure shape xyz", "app-D"),
]


def _seed_corpus(g):
    for ftype, sig, app in _CORPUS:
        g.upsert_failure(
            failure_type=ftype, signature_text=sig, app_id=app,
            impact_severity=Severity.medium,
        )


def _label_parity(g, threshold=0.6):
    g.mine_drain()
    _, vecs = g.records_and_embeddings()
    return np.array_equal(g._mine.labels(), cluster_embeddings(vecs, threshold=threshold))


def test_gfkb_ingest_attachment_matches_full_sweep(tmp_path):
    g = _mk(tmp_path)
    _seed_corpus(g)
    assert _label_parity(g)
    info = g.mine_state_info()
    assert info["enabled"] and not info["stale"] and info["covers_all_rows"]
    g.close()


def test_mine_patterns_incremental_equals_full(tmp_path):
    """Same corpus, two GFKBs: patterns emitted by incremental mining are
    byte-identical (name/fids/apps/description) to a forced full sweep."""

    def run(base, mode):
        g = _mk(base)
        det = PatternDetector(g)
        _seed_corpus(g)
        pats, info = det.mine_patterns_ex(0.6, mode)
        g.close()
        return {
            (p.name, tuple(p.failure_ids), tuple(sorted(p.affected_apps)), p.description)
            for p in pats
        }, info

    inc, inc_info = run(tmp_path / "inc", "auto")
    full, full_info = run(tmp_path / "full", "full")
    assert inc_info["mode"] == "incremental" and full_info["mode"] == "full"
    assert inc == full and inc  # identical and non-empty
    assert inc_info["wall_ms"] >= 0 and inc_info["covers_all_rows"]


def test_incremental_mine_reemits_only_dirty_clusters(tmp_path):
    g = _mk(tmp_path)
    det = PatternDetector(g)
    _seed_corpus(g)
    first, info = det.mine_patterns_ex(0.6)
    assert info["mode"] == "incremental" and first
    # quiescent corpus → nothing dirty → nothing re-emitted
    again, info = det.mine_patterns_ex(0.6)
    assert info["mode"] == "incremental" and again == []
    # one new row dirties exactly its cluster
    g.upsert_failure(
        failure_type="TIMEOUT",
        signature_text="timeout while calling payments api attempt 3",
        app_id="app-E", impact_severity=Severity.medium,
    )
    third, info = det.mine_patterns_ex(0.6)
    assert info["mode"] == "incremental"
    assert all("timeout" in p.name.lower() for p in third)
    g.close()


def test_warn_topk_reuse_skips_delta_dispatch(tmp_path):
    """The acceptance criterion: when the warn path already fetched a
    signature's neighbors, ingesting that signature attaches WITHOUT a
    new device dispatch; a cold signature costs exactly one. (Single-device
    mesh: the sharded match path needs jax.shard_map, unavailable in the
    CI image — same constraint as the chaos suite.)"""
    from kakveda_tpu.parallel.mesh import create_mesh

    g = _mk(tmp_path, mesh=create_mesh("data:1"))
    _seed_corpus(g)
    base = g.mine_delta_dispatches
    sig = "timeout while calling payments api attempt 9"
    g.match(sig)  # pre-flight warn fetches + caches the neighbors
    g.upsert_failure(
        failure_type="TIMEOUT", signature_text=sig, app_id="app-Z",
        impact_severity=Severity.medium,
    )
    assert g.mine_delta_dispatches == base  # reused, zero new dispatches
    assert _label_parity(g)  # and the attachment is still correct
    # cold signature (no warn first): exactly one delta dispatch
    g.upsert_failure(
        failure_type="SCHEMA", signature_text="another unseen failure shape pqr",
        app_id="app-Z", impact_severity=Severity.medium,
    )
    assert g.mine_delta_dispatches == base + 1
    assert _label_parity(g)
    g.close()


def test_incremental_disabled_reproduces_full_behavior(tmp_path, monkeypatch):
    """KAKVEDA_MINE_INCREMENTAL=0: no state, no dispatches, and
    mine_patterns emits exactly what the default path emits."""
    monkeypatch.setenv("KAKVEDA_MINE_INCREMENTAL", "0")
    g = _mk(tmp_path)
    det = PatternDetector(g)
    _seed_corpus(g)
    assert g._mine is None and g.mine_delta_dispatches == 0
    assert g.mine_state_info() == {"enabled": False}
    pats, info = det.mine_patterns_ex(0.6)
    assert info["mode"] == "full"
    monkeypatch.delenv("KAKVEDA_MINE_INCREMENTAL")
    g2 = _mk(tmp_path / "on")
    det2 = PatternDetector(g2)
    _seed_corpus(g2)
    pats2, _ = det2.mine_patterns_ex(0.6)
    key = lambda ps: {  # noqa: E731
        (p.name, tuple(p.failure_ids), tuple(sorted(p.affected_apps)), p.description)
        for p in ps
    }
    assert key(pats) == key(pats2)
    g.close()
    g2.close()


def test_threshold_change_full_sweep_then_reseeds(tmp_path):
    g = _mk(tmp_path)
    det = PatternDetector(g)
    _seed_corpus(g)
    assert det.mine_patterns_ex(0.6)[1]["mode"] == "incremental"
    _, info = det.mine_patterns_ex(0.5, "incremental")
    assert info["mode"] == "full" and info["fallback"]  # different graph
    # the sweep re-seeded the baseline at 0.5 → serveable incrementally now
    assert det.mine_patterns_ex(0.5)[1]["mode"] == "incremental"
    g.close()


def test_mine_mode_validation(tmp_path):
    g = _mk(tmp_path)
    with pytest.raises(ValueError):
        PatternDetector(g).mine_patterns(mode="bogus")
    g.close()


# ---------------------------------------------------------------------------
# snapshot v4: cluster labels ride the manifest, checksum-verified
# ---------------------------------------------------------------------------


def test_snapshot_restores_cluster_state(tmp_path):
    g = _mk(tmp_path)
    _seed_corpus(g)
    g.mine_drain()
    labels = g._mine.labels()
    g.snapshot()
    g.close()
    g2 = _mk(tmp_path)
    assert g2.mine_usable(0.6), g2.mine_state_info()
    assert np.array_equal(g2._mine.labels(), labels)
    # and a post-restore ingest keeps attaching incrementally
    g2.upsert_failure(
        failure_type="TIMEOUT", signature_text="timeout while calling payments api attempt 4",
        app_id="app-F", impact_severity=Severity.medium,
    )
    assert _label_parity(g2)
    g2.close()


def test_log_tail_beyond_snapshot_degrades_to_full_remine(tmp_path):
    """Rows appended after the snapshot are unknown to the persisted
    labels: restore must mark the state stale (one full re-mine), never
    serve a partial labeling."""
    g = _mk(tmp_path)
    _seed_corpus(g)
    g.snapshot()
    g.upsert_failure(
        failure_type="SCHEMA", signature_text="tail row after the snapshot",
        app_id="app-T", impact_severity=Severity.medium,
    )
    g.close()
    g2 = _mk(tmp_path)
    assert not g2.mine_usable(0.6)
    det = PatternDetector(g2)
    _, info = det.mine_patterns_ex(0.6)
    assert info["mode"] == "full"  # and the sweep re-seeds:
    assert g2.mine_usable(0.6)
    g2.close()


def test_corrupt_cluster_snapshot_degrades_to_full_remine_only(tmp_path):
    """A rotted clusters.npy costs ONE full re-mine — the records/vector
    restore is untouched (no full log replay, no re-embedding)."""
    g = _mk(tmp_path)
    _seed_corpus(g)
    g.mine_drain()
    g.snapshot()
    n = g.count
    g.close()
    cl = tmp_path / "data" / "snapshot" / "clusters.npy"
    cl.write_bytes(cl.read_bytes()[:-7] + b"garbage")
    g2 = _mk(tmp_path)
    assert g2.count == n  # record restore unaffected
    st = g2.mine_state_info()
    assert st["stale"]  # checksum refused the labels (reason may be the
    # restore failure or the post-replay coverage gap — both degrade)
    det = PatternDetector(g2)
    _, info = det.mine_patterns_ex(0.6)
    assert info["mode"] == "full"
    assert _label_parity(g2)  # re-seeded, trustworthy again
    g2.close()


# ---------------------------------------------------------------------------
# chaos: the gfkb.mine_state fault site (docs/robustness.md)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_mine_state_fault_on_attach_degrades_not_desyncs(tmp_path):
    """An injected cluster-state failure mid-ingest must (a) not fail the
    ingest, (b) latch the state stale, (c) cost exactly one full re-mine
    — after which incremental service resumes with correct labels."""
    g = _mk(tmp_path)
    det = PatternDetector(g)
    _seed_corpus(g)
    faults.arm("gfkb.mine_state:1:1")
    rec, created = g.upsert_failure(
        failure_type="TIMEOUT", signature_text="timeout while calling payments api attempt 5",
        app_id="app-G", impact_severity=Severity.medium,
    )
    assert created and rec.failure_id  # ingest survived the fault
    st = g.mine_state_info()
    assert st["stale"]
    _, info = det.mine_patterns_ex(0.6, "incremental")
    assert info["mode"] == "full" and info["fallback"]
    assert g.mine_usable(0.6) and _label_parity(g)  # healed via re-seed
    g.close()


@pytest.mark.chaos
def test_mine_state_fault_on_restore_degrades_to_full_remine(tmp_path):
    """Snapshot restore with the fault armed: labels are REFUSED (stale
    state), the vector/record restore is unaffected, and the next mine
    heals with one full sweep — never desynced labels."""
    g = _mk(tmp_path)
    _seed_corpus(g)
    g.mine_drain()
    g.snapshot()
    n = g.count
    g.close()
    faults.arm("gfkb.mine_state:1:1")
    g2 = _mk(tmp_path)
    assert g2.count == n
    st = g2.mine_state_info()
    assert st["stale"] and not g2.mine_usable(0.6)
    det = PatternDetector(g2)
    _, info = det.mine_patterns_ex(0.6)
    assert info["mode"] == "full"
    assert _label_parity(g2)
    g2.close()


# ---------------------------------------------------------------------------
# satellite: pow2 corpus padding keeps build_knn_edges compiles O(log N)
# ---------------------------------------------------------------------------


def test_build_knn_edges_compiles_once_per_pow2_bucket():
    """Growing the corpus across several _BLOCK boundaries inside one
    pow2 bucket must NOT respecialize _block_topk; crossing the bucket
    compiles exactly once more."""
    from kakveda_tpu.ops.clustering import _block_topk, build_knn_edges

    rng = np.random.default_rng(0)

    def corpus(n):
        v = rng.standard_normal((n, 64)).astype(np.float32)
        return v / np.linalg.norm(v, axis=1, keepdims=True)

    _block_topk.clear_cache()
    for n in (1100, 1500, 2047, 2048):  # three 1024-boundaries, one bucket
        build_knn_edges(corpus(n))
    assert _block_topk._cache_size() == 1, _block_topk._cache_size()
    build_knn_edges(corpus(2100))  # crosses into the 4096 bucket
    assert _block_topk._cache_size() == 2
