"""Sparse-MoE block (models/moe.py): routing/dispatch correctness vs a
per-token dense oracle, HF Mixtral logit parity, cached-decode parity,
expert-parallel sharding parity, capacity-drop semantics, and trainability
(gradients reach the router)."""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kakveda_tpu.models.llama import (
    LlamaConfig,
    forward,
    init_params,
    param_specs,
    specs_for_mesh,
)
from kakveda_tpu.models.moe import expert_capacity, load_balancing_loss, moe_mlp, router_topk


def _moe_cfg(**kw) -> LlamaConfig:
    base = dict(
        vocab_size=64,
        d_model=32,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_ff=48,
        max_seq_len=64,
        dtype=jnp.float32,
        n_experts=4,
        n_experts_per_tok=2,
    )
    base.update(kw)
    return LlamaConfig(**base)


def _oracle_moe(x: np.ndarray, layer, cfg: LlamaConfig) -> np.ndarray:
    """Per-token dense reference: every token runs its top-k experts
    directly, no dispatch buffers."""
    b, s, d = x.shape
    xf = x.reshape(-1, d)
    router = np.asarray(layer["router"], np.float32)
    logits = xf.astype(np.float32) @ router
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    out = np.zeros_like(xf, np.float32)
    k = cfg.n_experts_per_tok
    for t in range(xf.shape[0]):
        top = np.argsort(-probs[t])[:k]
        w = probs[t][top]
        w = w / w.sum()
        for wi, ei in zip(w, top):
            wg = np.asarray(layer["we_gate"][ei], np.float32)
            wu = np.asarray(layer["we_up"][ei], np.float32)
            wd = np.asarray(layer["we_down"][ei], np.float32)
            h = xf[t].astype(np.float32)
            gate = h @ wg
            gate = gate / (1.0 + np.exp(-gate))  # silu
            y = (gate * (h @ wu)) @ wd
            out[t] += wi * y
    return out.reshape(b, s, d)


def test_moe_mlp_matches_per_token_oracle():
    cfg = _moe_cfg()
    params = init_params(jax.random.PRNGKey(0), cfg)
    layer = params["layers"][0]
    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 5, cfg.d_model)), jnp.float32)
    got = np.asarray(moe_mlp(x, layer, cfg))
    want = _oracle_moe(np.asarray(x), layer, cfg)
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


def test_router_topk_renormalizes():
    logits = jnp.asarray(np.random.default_rng(1).standard_normal((7, 8)), jnp.float32)
    w, idx, probs = router_topk(logits, 2)
    np.testing.assert_allclose(np.asarray(w.sum(-1)), 1.0, atol=1e-6)
    assert np.asarray(probs).shape == (7, 8)
    # top-k indices really are the argmax-ordered experts
    assert (np.asarray(idx[:, 0]) == np.asarray(probs).argmax(-1)).all()


def test_expert_capacity_factor():
    cfg = _moe_cfg(expert_capacity_factor=0.0)
    assert expert_capacity(100, cfg) == 100  # no-drop
    cfg = _moe_cfg(expert_capacity_factor=1.0)
    # T·k/E = 100·2/4 = 50
    assert expert_capacity(100, cfg) == 50
    assert expert_capacity(3, _moe_cfg(expert_capacity_factor=0.01)) == 1


def test_capacity_drop_changes_output_but_stays_finite():
    cfg_exact = _moe_cfg()
    cfg_tight = _moe_cfg(expert_capacity_factor=0.3)
    params = init_params(jax.random.PRNGKey(2), cfg_exact)
    layer = params["layers"][0]
    x = jnp.asarray(np.random.default_rng(2).standard_normal((1, 32, cfg_exact.d_model)), jnp.float32)
    exact = np.asarray(moe_mlp(x, layer, cfg_exact))
    dropped = np.asarray(moe_mlp(x, layer, cfg_tight))
    assert np.isfinite(dropped).all()
    assert np.abs(exact - dropped).max() > 1e-6  # the cap actually bit


def test_moe_forward_and_decode_parity(decode_parity):
    """Full forward on an MoE config, and the cached decode path must
    reproduce its greedy continuation exactly (dispatch inside decode
    operates on T = B tokens)."""
    cfg = _moe_cfg()
    params = init_params(jax.random.PRNGKey(3), cfg)
    decode_parity(params, cfg, list(range(5, 17)), n=6)


def test_moe_ep_sharded_forward_parity():
    """Experts sharded over an ep×tp submesh produce the same logits as the
    unsharded forward — XLA inserts the dispatch/combine collectives."""
    from jax.sharding import NamedSharding

    from kakveda_tpu.parallel.mesh import create_mesh

    cfg = _moe_cfg()
    params = init_params(jax.random.PRNGKey(4), cfg)
    ids = jnp.asarray(np.random.default_rng(4).integers(0, 64, size=(2, 9)))
    want = np.asarray(forward(params, cfg, ids))

    mesh = create_mesh("dp:2,ep:2,tp:2")
    specs = specs_for_mesh(param_specs(cfg), mesh)
    sharded = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )
    we = sharded["layers"][0]["we_gate"]
    assert we.sharding.spec == specs["layers"][0]["we_gate"]
    got = np.asarray(forward(sharded, cfg, ids))
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


def test_specs_for_mesh_drops_absent_axes():
    from jax.sharding import PartitionSpec as P

    from kakveda_tpu.parallel.mesh import create_mesh

    cfg = _moe_cfg()
    mesh = create_mesh("dp:2,tp:2")  # no ep axis
    specs = specs_for_mesh(param_specs(cfg), mesh)
    assert specs["layers"][0]["we_gate"] == P(None, None, "tp")
    assert specs["layers"][0]["we_down"] == P(None, "tp", None)


def test_load_balancing_loss_uniform_is_top_k():
    # HF load_balancing_loss_func convention: counts normalize by T (each
    # token contributes top_k assignments), so the uniform minimum is
    # top_k and the one-expert collapse approaches E·top_k.
    t, e, k = 64, 4, 2
    probs = jnp.full((t, e), 1.0 / e)
    # perfectly balanced assignments
    idx = jnp.asarray(np.stack([np.arange(t) % e, (np.arange(t) + 1) % e], -1))
    loss = float(load_balancing_loss(probs, idx, e, k))
    assert abs(loss - k) < 1e-5
    # collapse onto one expert: loss rises toward E·k
    probs_bad = jnp.zeros((t, e)).at[:, 0].set(1.0)
    idx_bad = jnp.zeros((t, k), jnp.int32)
    assert float(load_balancing_loss(probs_bad, idx_bad, e, k)) > 2 * 3.9


def test_aux_loss_wired_into_training_objective():
    """router_aux_coef > 0 adds the summed per-layer load-balancing loss
    to lm_loss; the aux term sits in [top_k, E·top_k] per layer (HF
    normalization)."""
    from kakveda_tpu.models.train import lm_loss

    cfg0 = _moe_cfg()
    cfg1 = _moe_cfg(router_aux_coef=0.5)
    params = init_params(jax.random.PRNGKey(6), cfg0)
    tokens = jnp.asarray(np.random.default_rng(6).integers(0, 64, size=(2, 16)))
    base = float(lm_loss(params, cfg0, tokens))
    with_aux = float(lm_loss(params, cfg1, tokens))
    per_layer_aux = (with_aux - base) / (0.5 * cfg0.n_layers)
    k = cfg0.n_experts_per_tok
    assert k - 1e-3 <= per_layer_aux <= cfg0.n_experts * k + 1e-3, per_layer_aux
    # aux still differentiates
    g = jax.grad(lm_loss)(params, cfg1, tokens)
    assert np.isfinite(float(jnp.abs(g["layers"][0]["router"]).max()))


def test_moe_gradients_reach_router_and_experts():
    from kakveda_tpu.models.train import lm_loss

    cfg = _moe_cfg()
    params = init_params(jax.random.PRNGKey(5), cfg)
    tokens = jnp.asarray(np.random.default_rng(5).integers(0, 64, size=(2, 16)))
    loss, grads = jax.value_and_grad(lm_loss)(params, cfg, tokens)
    assert np.isfinite(float(loss))
    g = grads["layers"][0]
    for key in ("router", "we_gate", "we_up", "we_down"):
        gn = float(jnp.abs(g[key]).max())
        assert np.isfinite(gn) and gn > 0.0, key
