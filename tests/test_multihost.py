"""Multi-host (multi-controller) proof: a real 2-process jax.distributed
cluster on CPU devices.

VERDICT round-1 item 4: the sharded-index insert/match and the train step
were asserted multi-host-safe but never exercised with process_count > 1.
Here two OS processes form a jax.distributed world (4 CPU devices each →
one 8-device global mesh), then run:

  * ShardedKnn alloc → insert → cross-shard top-k match (the GFKB core),
  * one dp×tp sharded train step on the in-tree Llama,

and assert both produce identical, correct results on every process.
Multi-host orchestration matches kakveda_tpu.parallel.distributed
(KAKVEDA_COORDINATOR / NUM_PROCESSES / PROCESS_ID).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

import pytest

_CHILD = r"""
import os, sys
import numpy as np

import jax
# The image's sitecustomize pins the axon TPU plugin; JAX_PLATFORMS=cpu env
# alone does not override it (same dance as tests/conftest.py).
jax.config.update("jax_platforms", "cpu")

from kakveda_tpu.parallel.distributed import initialize_multihost

assert initialize_multihost(), "multihost env not picked up"

assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, len(jax.devices())

import jax.numpy as jnp

from kakveda_tpu.ops.knn import ShardedKnn
from kakveda_tpu.parallel.mesh import create_mesh

# --- sharded index: alloc + insert + cross-shard match -------------------
mesh = create_mesh("data:8")
knn = ShardedKnn(mesh, capacity=128, dim=128, k=5)
emb, valid = knn.alloc()
rng = np.random.default_rng(0)  # same seed everywhere: replicated inputs
vecs = rng.standard_normal((32, 128)).astype(np.float32)
vecs /= np.linalg.norm(vecs, axis=1, keepdims=True)
emb, valid = knn.insert(emb, valid, vecs, np.arange(32, dtype=np.int32))
types = knn.alloc_i32()
types = knn.scatter_i32(types, np.arange(32, dtype=np.int32), np.arange(32, dtype=np.int32) % 3)
scores, slots = knn.topk(emb, valid, vecs[:4])
assert scores.shape == (4, 5), scores.shape
assert np.all(scores[:, 0] > 0.99), scores[:, 0]
assert list(slots[:, 0]) == [0, 1, 2, 3], slots[:, 0]
# device-side type mask: query 0's type-0 rows only
masked = knn.mask_valid(valid, types, 0)
mscores, mslots = knn.topk(emb, masked, vecs[:4])
assert all(s % 3 == 0 for s in mslots[0] if s < 32), mslots[0]

# --- one sharded train step ---------------------------------------------
from kakveda_tpu.models.llama import LlamaConfig
from kakveda_tpu.models.train import make_sharded_train_step

tmesh = create_mesh("dp:2,cp:2,tp:2")
cfg = LlamaConfig(
    vocab_size=264, d_model=64, n_layers=2, n_heads=8, n_kv_heads=4,
    d_ff=128, max_seq_len=64, dtype=jnp.float32,
)
step, init_state = make_sharded_train_step(cfg, tmesh)
params, opt_state = init_state(jax.random.PRNGKey(0))
tokens = jnp.asarray(np.random.default_rng(0).integers(3, 259, size=(4, 32)), jnp.int32)
params, opt_state, loss = step(params, opt_state, tokens)
loss_val = float(loss)
assert np.isfinite(loss_val), loss_val

# --- host->mesh placement of a checkpoint-shaped tree --------------------
from kakveda_tpu.models.train import shard_params
from kakveda_tpu.models.llama import init_params
host_params = jax.tree.map(lambda x: np.asarray(x), init_params(jax.random.PRNGKey(1), cfg))
placed = shard_params(host_params, cfg, tmesh)
assert not placed["layers"][0]["wq"].sharding.is_fully_addressable

# --- GFKB snapshot discipline: collective gather, symmetric writes -------
# Per-host data dirs (the deployment contract: a shared dir would double-
# append the log). snapshot() is collective — EVERY process calls it and
# writes its own dir — so a later restore runs IDENTICAL insert programs
# on every host (a restored-vs-replayed mix desynchronizes SPMD lockstep).
from kakveda_tpu.core.schemas import Severity
from kakveda_tpu.index.gfkb import GFKB

data_dir = os.environ["KAKVEDA_TEST_DATA_DIR"] + f"/host-{jax.process_index()}"
kb = GFKB(data_dir=data_dir, capacity=64, dim=256)
for i in range(6):
    kb.upsert_failure(
        failure_type="T",
        signature_text=f"sig number {i} about topic {i * 3}",
        app_id=f"app-{i % 2}",
        impact_severity=Severity.low,
    )
sd = kb.snapshot()  # collective: both processes participate + write
assert (sd / "manifest.json").exists(), f"p{jax.process_index()} missing snapshot"
kb.upsert_failure(  # post-snapshot tail, must replay on restore
    failure_type="T", signature_text="tail sig after snapshot", app_id="app-9",
    impact_severity=Severity.low,
)
kb.close()
kb2 = GFKB(data_dir=data_dir, capacity=64, dim=256)  # restore + tail replay
assert kb2.count == 7, kb2.count
m = kb2.match("tail sig after snapshot")
assert m and m[0].score > 0.99, m
snap_ok = "snap-restored"

print(f"MULTIHOST_OK p{jax.process_index()} loss={loss_val:.6f} top1={float(scores[0,0]):.4f} snap={snap_ok}")
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.skipif(
    tuple(int(x) for x in __import__("jax").__version__.split(".")[:2]) < (0, 5),
    reason="pre-existing failure on old jax (<0.5): the two-process CPU "
    "coordinator wedges during distributed init on this jax/jaxlib pair; "
    "passes on current jax",
)
def test_two_process_cluster(tmp_path):
    port = _free_port()
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            KAKVEDA_COORDINATOR=f"127.0.0.1:{port}",
            KAKVEDA_NUM_PROCESSES="2",
            KAKVEDA_PROCESS_ID=str(pid),
            KAKVEDA_TEST_DATA_DIR=str(tmp_path / "data"),
            PYTHONPATH="/root/repo" + os.pathsep + env.get("PYTHONPATH", ""),
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script)],
                env=env,
                cwd="/root/repo",
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=300)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out[-4000:]}"
        assert f"MULTIHOST_OK p{pid}" in out, out[-2000:]
    # Both processes computed the SAME loss — the SPMD contract held.
    lines = [next(l for l in o.splitlines() if "MULTIHOST_OK" in l) for o in outs]
    assert lines[0].split("loss=")[1] == lines[1].split("loss=")[1], lines
