"""C++ native tier: crc32/featurizer parity vs the Python reference
implementation, and the append-log writer."""

import random
import string
import zlib

import numpy as np
import pytest

from kakveda_tpu import native
from kakveda_tpu.core.fingerprint import signature_text
from kakveda_tpu.ops.featurizer import HashedNGramFeaturizer

lib = native.load()
needs_native = pytest.mark.skipif(lib is None, reason="native library unavailable")


@needs_native
def test_crc32_parity():
    rng = random.Random(0)
    cases = [b"", b"a", b"hello world", bytes(range(256))]
    cases += [
        "".join(rng.choices(string.printable, k=rng.randint(1, 200))).encode()
        for _ in range(50)
    ]
    for c in cases:
        assert lib.kkv_crc32(c, len(c)) == zlib.crc32(c)


@needs_native
def test_featurizer_parity_structured_and_random():
    f = HashedNGramFeaturizer(dim=1024)
    rng = random.Random(1)
    alphabet = string.ascii_letters + string.digits + " _:,|.!?-"
    texts = [
        signature_text(
            "Summarize this document and include citations even if not provided.",
            [],
            {"os": "linux"},
        ),
        signature_text("Explain with references.", ["search", "browse"], {"a": 1, "b": 2}),
        "free form text with no fields",
        "intent_tags: a, b , c | prompt_hint: Hello World_9 | tools:  | env_keys: os",
        "",
        " | ",
        "UNKNOWN_Field: Stuff Here | intent_tags: x",
        "trailing field sep | ",
    ] + ["".join(rng.choices(alphabet, k=rng.randint(0, 300))) for _ in range(100)]
    a = f._encode_batch_py(texts)
    b = f._encode_batch_native(lib, texts)
    assert ((a != 0) == (b != 0)).all(), "bucket support must match exactly"
    np.testing.assert_allclose(a, b, atol=2e-7)


@needs_native
def test_featurizer_nonascii_falls_back():
    f = HashedNGramFeaturizer(dim=256)
    texts = ["prompt_hint: café résumé", "plain ascii"]
    out = f.encode_batch(texts)  # must not crash; routes through Python
    ref = f._encode_batch_py(texts)
    np.testing.assert_array_equal(out, ref)


def test_featurizer_env_disable(monkeypatch):
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_load_attempted", False)
    monkeypatch.setenv("KAKVEDA_NATIVE", "0")
    assert native.load() is None
    f = HashedNGramFeaturizer(dim=256)
    v = f.encode_batch(["still works via python"])
    assert v.shape == (1, 256) and np.isclose(np.linalg.norm(v[0]), 1.0)
    monkeypatch.setattr(native, "_load_attempted", False)


def test_append_log_roundtrip(tmp_path):
    p = tmp_path / "log.jsonl"
    with native.AppendLog(p) as log:
        for i in range(100):
            log.append(f'{{"i": {i}}}\n'.encode())
        log.flush(fsync=True)
        lines = p.read_text().splitlines()
        assert len(lines) == 100 and lines[42] == '{"i": 42}'
    # append mode: reopening continues the log
    with native.AppendLog(p) as log:
        log.append(b'{"i": 100}\n')
        log.flush()
        assert len(p.read_text().splitlines()) == 101


def test_append_log_python_fallback(tmp_path, monkeypatch):
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_load_attempted", False)
    monkeypatch.setenv("KAKVEDA_NATIVE", "0")
    p = tmp_path / "log.jsonl"
    with native.AppendLog(p) as log:
        assert not log.native
        log.append(b"x\n")
        log.flush(fsync=True)
    assert p.read_text() == "x\n"
    monkeypatch.setattr(native, "_load_attempted", False)


@needs_native
def test_gfkb_appends_visible_after_upsert(tmp_path):
    """Group-commit must still give read-your-writes after each public op."""
    from kakveda_tpu.index.gfkb import GFKB

    idx = GFKB(data_dir=tmp_path, capacity=64, dim=256)
    idx.upsert_failure(
        failure_type="HALLUCINATION_CITATION",
        signature_text="intent_tags: intent:citations_required | prompt_hint: x",
        app_id="app-A",
        impact_severity="medium",
    )
    text = (tmp_path / "failures.jsonl").read_text()
    assert text.count("\n") == 1 and "F-0001" in text
    idx.close()


def test_sparse_encode_native_python_parity():
    """The C++ sparse encoder and the Python fallback (dense + nonzero)
    must produce the same DENSIFIED rows — entry order inside a row may
    differ, so compare through the scatter semantics, and exercise the
    grow-and-retry path with a >64-feature text."""
    import numpy as np

    from kakveda_tpu import native
    from kakveda_tpu.ops.featurizer import HashedNGramFeaturizer

    if not native.available():
        import pytest

        pytest.skip("native library unavailable")

    feat = HashedNGramFeaturizer(dim=512)
    texts = [
        "intent_tags:intent:citations_required,task:summarization | prompt_hint:summarize the report | tools:search,browse | env_keys:os,region",
        "plain free-form text without any field structure at all",
        "",
        # >64 unique grams → native returns required-K and the wrapper retries
        " ".join(f"word{i}" for i in range(90)),
    ]
    n_idx, n_val = feat._encode_sparse_native(native.load(), texts)
    dense = feat.encode_batch(texts)

    def densify(idx, val):
        out = np.zeros((idx.shape[0], feat.dim), np.float32)
        for r in range(idx.shape[0]):
            for c, v in zip(idx[r], val[r]):
                if c < feat.dim:
                    out[r, c] += v
        return out

    np.testing.assert_allclose(densify(n_idx, n_val), dense, atol=1e-6)
    assert n_idx.shape[1] >= 128  # grew past the 64 floor for the long text
