"""Native host-tier scoring engine (ISSUE 11): C++ vs numpy parity over
the warm / cold / IVF paths, the KAKVEDA_NATIVE=0 bit-for-bit contract,
the ``require`` build smoke, and the ``native.score`` chaos site
(armed → numpy fallback, never a failed match).
"""

import numpy as np
import pytest

from kakveda_tpu import native
from kakveda_tpu.core import faults
from kakveda_tpu.index.tiers import TierConfig, TieredIndex

lib = native.load()
needs_native = pytest.mark.skipif(lib is None, reason="native library unavailable")


@pytest.fixture(autouse=True)
def _disarmed():
    faults.disarm()
    yield
    faults.disarm()


def _clustered_corpus(n, dim, n_templates, k=12, seed=11):
    rng = np.random.default_rng(seed)
    tmpl = rng.integers(0, dim, size=(n_templates, k), dtype=np.int64)
    t = rng.integers(0, n_templates, size=n)
    idx = tmpl[t].astype(np.int32)
    val = (1.0 + 0.1 * rng.standard_normal((n, k))).astype(np.float32)
    val /= np.maximum(np.linalg.norm(val, axis=1, keepdims=True), 1e-9)
    return idx, val, rng


def _build(n, dim, cfg, data_dir=None, seed=11):
    idx, val, rng = _clustered_corpus(n, dim, n_templates=40, seed=seed)
    tiers = TieredIndex(dim, cfg, data_dir)
    for s in range(0, n, 256):
        e = min(n, s + 256)
        tiers.insert(np.arange(s, e), idx[s:e], val[s:e])
    return tiers, idx, val, rng


def _queries(idx, val, rng, m):
    out = []
    for qi in rng.integers(0, len(idx), size=m).tolist():
        q_val = val[qi] + 0.05 * rng.standard_normal(idx.shape[1]).astype(np.float32)
        q_val /= max(float(np.linalg.norm(q_val)), 1e-9)
        out.append((idx[qi], q_val))
    return out


def _run(tiers, queries, *, exact):
    return [tiers.match_host(q_idx, q_val, 5, exact=exact) for q_idx, q_val in queries]


def _assert_topk_parity(res_a, res_b):
    """Same top-k ids and scores within 1e-5 (float summation-order ties
    may swap ids of equal-score rows — accept an id swap only when the
    scores tie within tolerance)."""
    for (sc_a, sl_a, _), (sc_b, sl_b, _) in zip(res_a, res_b):
        np.testing.assert_allclose(sc_a, sc_b, atol=1e-5)
        for j, (a, b) in enumerate(zip(sl_a, sl_b)):
            assert a == b or abs(float(sc_a[j]) - float(sc_b[j])) <= 1e-5


# ---------------------------------------------------------------------------
# native vs numpy parity, per path
# ---------------------------------------------------------------------------


@needs_native
def test_warm_exact_scan_parity():
    """Warm-tier exact scan: the C++ row sweep and the inverted-index
    walk must agree on top-k ids and scores."""
    tiers, idx, val, rng = _build(2500, 512, TierConfig(tiered=True, hot_rows=0, nprobe=8))
    assert tiers.scorer.enabled
    qs = _queries(idx, val, rng, 32)
    before = tiers.scorer._h["warm"].count
    res_native = _run(tiers, qs, exact=True)
    assert tiers.scorer._h["warm"].count > before, "native warm path never ran"
    tiers.scorer.enabled = False
    res_numpy = _run(tiers, qs, exact=True)
    tiers.scorer.enabled = True
    _assert_topk_parity(res_native, res_numpy)


@needs_native
def test_ivf_routed_parity_single_and_batch():
    """Routed matching: native candidate scoring (per-query block and the
    batched thread-pooled call) agrees with the numpy fallback."""
    tiers, idx, val, rng = _build(2500, 512, TierConfig(tiered=True, hot_rows=0, nprobe=8))
    qs = _queries(idx, val, rng, 32)
    res_native = _run(tiers, qs, exact=False)
    q_idx = np.stack([q[0] for q in qs])
    q_val = np.stack([q[1] for q in qs])
    before = tiers.scorer._h["ivf"].count
    res_batch_native = tiers.match_host_batch(q_idx, q_val, 5, exact=False)
    assert tiers.scorer._h["ivf"].count > before, "native ivf path never ran"
    tiers.scorer.enabled = False
    res_numpy = _run(tiers, qs, exact=False)
    res_batch_numpy = tiers.match_host_batch(q_idx, q_val, 5, exact=False)
    tiers.scorer.enabled = True
    _assert_topk_parity(res_native, res_numpy)
    _assert_topk_parity(res_batch_native, res_batch_numpy)
    _assert_topk_parity(res_batch_native, res_native)


@needs_native
def test_cold_shard_scan_parity(tmp_path):
    """Cold memmap shards: native per-shard sweep vs the chunked numpy
    scan, through the exact match path of a spilled corpus."""
    cfg = TierConfig(
        tiered=True, hot_rows=0, warm_rows=512, nprobe=4,
        cold_dir=tmp_path / "cold",
    )
    tiers, idx, val, rng = _build(2000, 512, cfg, data_dir=tmp_path)
    assert tiers.info()["cold"] > 0, "corpus never spilled to cold"
    qs = _queries(idx, val, rng, 16)
    before = tiers.scorer._h["cold"].count
    res_native = _run(tiers, qs, exact=True)
    assert tiers.scorer._h["cold"].count > before, "native cold path never ran"
    tiers.scorer.enabled = False
    res_numpy = _run(tiers, qs, exact=True)
    tiers.scorer.enabled = True
    _assert_topk_parity(res_native, res_numpy)


@needs_native
def test_score_block_clamps_pad_and_negative_ids():
    """Raw kernel property: pad (== dim) and negative ids score 0 exactly
    like the numpy clamp expression — malformed rows degrade a score,
    never read out of bounds."""
    rng = np.random.default_rng(0)
    dim, n, k = 64, 300, 8
    idx = rng.integers(-3, dim + 1, size=(n, k)).astype(np.int32)
    val = rng.standard_normal((n, k)).astype(np.float32)
    qd = np.zeros(dim + 1, np.float32)
    qd[:dim] = rng.standard_normal(dim).astype(np.float32)
    out = native.score_block(qd, idx, val, dim)
    assert out is not None
    clamped = np.where((idx < 0) | (idx >= dim), dim, idx)
    ref = (qd[clamped] * val).sum(axis=1)
    np.testing.assert_allclose(out, ref, atol=1e-5)


# ---------------------------------------------------------------------------
# KAKVEDA_NATIVE=0 / require / fault contracts
# ---------------------------------------------------------------------------


def test_native_off_bit_for_bit(monkeypatch):
    """KAKVEDA_NATIVE=0: the scorer stays disabled and the batch path's
    numpy fallback reproduces the per-query numpy path EXACTLY (same
    gathered rows, same expression — bit-for-bit, not just within
    tolerance)."""
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_load_attempted", False)
    monkeypatch.setenv("KAKVEDA_NATIVE", "0")
    try:
        tiers, idx, val, rng = _build(
            1500, 512, TierConfig(tiered=True, hot_rows=0, nprobe=8)
        )
        assert not tiers.scorer.enabled
        qs = _queries(idx, val, rng, 16)
        res_single = _run(tiers, qs, exact=False)
        res_batch = tiers.match_host_batch(
            np.stack([q[0] for q in qs]), np.stack([q[1] for q in qs]), 5,
            exact=False,
        )
        for (sc_a, sl_a, mode_a), (sc_b, sl_b, mode_b) in zip(res_single, res_batch):
            assert mode_a == mode_b
            np.testing.assert_array_equal(sl_a, sl_b)
            np.testing.assert_array_equal(sc_a, sc_b)  # bit-for-bit
        # exact scans identical too (scorer off on both paths)
        e_single = _run(tiers, qs, exact=True)
        for (sc, sl, mode) in e_single:
            assert mode == "exact" and len(sl) == 5
    finally:
        monkeypatch.setattr(native, "_load_attempted", False)


@needs_native
def test_native_require_smoke(monkeypatch):
    """KAKVEDA_NATIVE=require must load (the in-tree build works here) and
    status() reports it."""
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_load_attempted", False)
    monkeypatch.setenv("KAKVEDA_NATIVE", "require")
    try:
        assert native.load() is not None
        st = native.status()
        assert st["available"] and st["mode"] == "require" and st["threads"] >= 1
    finally:
        monkeypatch.setattr(native, "_lib", None)
        monkeypatch.setattr(native, "_load_attempted", False)


@needs_native
@pytest.mark.chaos
def test_native_score_fault_falls_back():
    """Chaos site native.score: armed, every scoring call degrades to the
    numpy path — identical results, fallback counter incremented, and the
    match NEVER fails."""
    tiers, idx, val, rng = _build(2500, 512, TierConfig(tiered=True, hot_rows=0, nprobe=8))
    qs = _queries(idx, val, rng, 8)
    res_native = _run(tiers, qs, exact=True)
    before = tiers.scorer._c_fb["fault"].value
    faults.arm("native.score:1:-1")
    try:
        res_fault = _run(tiers, qs, exact=True)
        r_sc, r_sl, r_mode = tiers.match_host(qs[0][0], qs[0][1], 5, exact=False)
        assert r_mode == "routed" and len(r_sl)
    finally:
        faults.disarm()
    assert tiers.scorer._c_fb["fault"].value > before
    _assert_topk_parity(res_native, res_fault)
    # disarmed: native serves again
    res_after = _run(tiers, qs[:2], exact=True)
    _assert_topk_parity(res_native[:2], res_after)


@needs_native
def test_min_rows_floor_keeps_tiny_scans_numpy():
    """Scans under KAKVEDA_NATIVE_MIN_ROWS stay on the numpy path — a
    policy choice, so no fallback is counted either."""
    tiers, idx, val, rng = _build(64, 256, TierConfig(tiered=True, hot_rows=0, nprobe=4))
    tiers.scorer.min_rows = 1 << 20
    h_before = sum(h.count for h in tiers.scorer._h.values())
    fb_before = sum(c.value for c in tiers.scorer._c_fb.values())
    sc, sl, _mode = tiers.match_host(idx[5], val[5], 3, exact=True)
    assert len(sl)
    assert sum(h.count for h in tiers.scorer._h.values()) == h_before
    assert sum(c.value for c in tiers.scorer._c_fb.values()) == fb_before
