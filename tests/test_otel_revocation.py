"""OTel bootstrap (best-effort, gated) and JWT revocation on logout."""

import asyncio
import time

from aiohttp.test_utils import TestClient, TestServer

from kakveda_tpu.core import otel
from kakveda_tpu.core.revocation import RevocationStore
from kakveda_tpu.dashboard.app import make_dashboard_app
from kakveda_tpu.models.runtime import StubRuntime
from kakveda_tpu.platform import Platform


def run(coro):
    return asyncio.run(coro)


def test_revocation_store_memory_ttl():
    rs = RevocationStore(redis_url=None)
    rs.revoke("jti-1", time.time() + 60)
    assert rs.is_revoked("jti-1")
    assert not rs.is_revoked("jti-2")
    rs.revoke("jti-old", time.time() - 1)
    assert not rs.is_revoked("jti-old"), "expired revocations fall away"


def test_logout_revokes_token(tmp_path):
    async def go():
        plat = Platform(data_dir=tmp_path / "data", capacity=256, dim=1024)
        app = make_dashboard_app(platform=plat, db_path=tmp_path / "dash.db", model=StubRuntime())
        client = await _client(app)
        try:
            r = await client.post(
                "/login",
                data={"email": "admin@local", "password": "admin123", "next": "/"},
                allow_redirects=False,
            )
            assert r.status == 302
            token = client.session.cookie_jar.filter_cookies(client.make_url("/"))[
                "kakveda_token"
            ].value

            r = await client.get("/", allow_redirects=False)
            assert r.status == 200

            await client.post("/logout", allow_redirects=False)
            # Replay the captured (stolen) token: must no longer authenticate.
            r = await client.get(
                "/", headers={"Cookie": f"kakveda_token={token}"}, allow_redirects=False
            )
            assert r.status == 302 and "/login" in r.headers["Location"]
        finally:
            await client.close()

    run(go())


async def _client(app):
    client = TestClient(TestServer(app))
    await client.start_server()
    return client


def test_otel_disabled_by_default(tmp_path, monkeypatch):
    monkeypatch.setattr(otel, "_setup_done", False)
    monkeypatch.setattr(otel, "_tracer", None)
    monkeypatch.delenv("KAKVEDA_OTEL_ENABLED", raising=False)
    assert otel.setup_otel("test") is False
    assert otel.get_tracer() is None


def test_otel_enabled_creates_tracer(monkeypatch):
    monkeypatch.setattr(otel, "_setup_done", False)
    monkeypatch.setattr(otel, "_tracer", None)
    monkeypatch.setenv("KAKVEDA_OTEL_ENABLED", "1")
    ok = otel.setup_otel("test")
    try:
        import opentelemetry.sdk  # noqa: F401

        assert ok is True and otel.get_tracer() is not None
    except ImportError:
        # SDK absent: enabling must degrade to a no-op, never crash.
        assert ok is False and otel.get_tracer() is None
    # middleware wraps a handler without breaking it
    from aiohttp import web

    async def go():
        app = web.Application(middlewares=[otel.otel_middleware()])

        async def hello(request):
            return web.json_response({"ok": True})

        app.router.add_get("/", hello)
        client = await _client(app)
        try:
            r = await client.get("/")
            assert r.status == 200 and (await r.json())["ok"]
        finally:
            await client.close()

    run(go())
    monkeypatch.setattr(otel, "_setup_done", False)
    monkeypatch.setattr(otel, "_tracer", None)


def test_make_app_and_dashboard_install_otel_middleware(tmp_path, monkeypatch):
    """The satellite contract: when otel is enabled, BOTH app factories
    actually install the otel middleware (outermost, so the span covers
    the request-context middleware too)."""
    from kakveda_tpu.service.app import make_app

    sentinel = object()
    monkeypatch.setattr(otel, "setup_otel", lambda name: True)
    monkeypatch.setattr(otel, "otel_middleware", lambda: sentinel)

    plat = Platform(data_dir=tmp_path / "data", capacity=256, dim=1024)
    app = make_app(plat)
    assert app.middlewares[0] is sentinel

    dash = make_dashboard_app(
        platform=plat, db_path=tmp_path / "dash.db", model=StubRuntime()
    )
    assert dash.middlewares[0] is sentinel


def test_otel_middleware_records_request_id_and_span_events(monkeypatch):
    """With a (fake) tracer installed, the server span carries request.id
    equal to the echoed x-request-id header, and add_span_events attaches
    the serving timeline (non-scalar values dropped) to the current span."""
    import contextlib
    import sys
    import types

    from aiohttp import web

    from kakveda_tpu.service.app import request_context_middleware

    recorded = {}

    class FakeSpan:
        def set_attribute(self, k, v):
            recorded[k] = v

        def add_event(self, name, attrs):
            recorded.setdefault("events", []).append((name, dict(attrs)))

        def is_recording(self):
            return True

        def set_status(self, s):
            pass

    fake_span = FakeSpan()

    tr = types.ModuleType("opentelemetry.trace")
    tr.SpanKind = types.SimpleNamespace(SERVER="server")
    tr.Status = lambda code, desc=None: (code, desc)
    tr.StatusCode = types.SimpleNamespace(ERROR="error")
    tr.get_current_span = lambda: fake_span
    ot = types.ModuleType("opentelemetry")
    ot.trace = tr
    monkeypatch.setitem(sys.modules, "opentelemetry", ot)
    monkeypatch.setitem(sys.modules, "opentelemetry.trace", tr)

    class FakeTracer:
        @contextlib.contextmanager
        def start_as_current_span(self, name, kind=None):
            yield fake_span

    monkeypatch.setattr(otel, "_tracer", FakeTracer())

    async def go():
        app = web.Application(
            middlewares=[otel.otel_middleware(), request_context_middleware]
        )

        async def ping(request):
            return web.json_response({"ok": True})

        app.router.add_get("/ping", ping)
        client = await _client(app)
        try:
            r = await client.get("/ping", headers={"x-request-id": "rid-123"})
            assert r.status == 200
            # one id end to end: span attribute == echoed header
            assert r.headers["x-request-id"] == "rid-123"
        finally:
            await client.close()

    run(go())
    assert recorded["request.id"] == "rid-123"
    assert recorded["http.response.status_code"] == 200

    otel.add_span_events("serving.timeline", {"ttft_ms": 1.5, "refs": [1, 2]})
    assert ("serving.timeline", {"ttft_ms": 1.5}) in recorded["events"]
