"""OTel bootstrap (best-effort, gated) and JWT revocation on logout."""

import asyncio
import time

from aiohttp.test_utils import TestClient, TestServer

from kakveda_tpu.core import otel
from kakveda_tpu.core.revocation import RevocationStore
from kakveda_tpu.dashboard.app import make_dashboard_app
from kakveda_tpu.models.runtime import StubRuntime
from kakveda_tpu.platform import Platform


def run(coro):
    return asyncio.run(coro)


def test_revocation_store_memory_ttl():
    rs = RevocationStore(redis_url=None)
    rs.revoke("jti-1", time.time() + 60)
    assert rs.is_revoked("jti-1")
    assert not rs.is_revoked("jti-2")
    rs.revoke("jti-old", time.time() - 1)
    assert not rs.is_revoked("jti-old"), "expired revocations fall away"


def test_logout_revokes_token(tmp_path):
    async def go():
        plat = Platform(data_dir=tmp_path / "data", capacity=256, dim=1024)
        app = make_dashboard_app(platform=plat, db_path=tmp_path / "dash.db", model=StubRuntime())
        client = await _client(app)
        try:
            r = await client.post(
                "/login",
                data={"email": "admin@local", "password": "admin123", "next": "/"},
                allow_redirects=False,
            )
            assert r.status == 302
            token = client.session.cookie_jar.filter_cookies(client.make_url("/"))[
                "kakveda_token"
            ].value

            r = await client.get("/", allow_redirects=False)
            assert r.status == 200

            await client.post("/logout", allow_redirects=False)
            # Replay the captured (stolen) token: must no longer authenticate.
            r = await client.get(
                "/", headers={"Cookie": f"kakveda_token={token}"}, allow_redirects=False
            )
            assert r.status == 302 and "/login" in r.headers["Location"]
        finally:
            await client.close()

    run(go())


async def _client(app):
    client = TestClient(TestServer(app))
    await client.start_server()
    return client


def test_otel_disabled_by_default(tmp_path, monkeypatch):
    monkeypatch.setattr(otel, "_setup_done", False)
    monkeypatch.setattr(otel, "_tracer", None)
    monkeypatch.delenv("KAKVEDA_OTEL_ENABLED", raising=False)
    assert otel.setup_otel("test") is False
    assert otel.get_tracer() is None


def test_otel_enabled_creates_tracer(monkeypatch):
    monkeypatch.setattr(otel, "_setup_done", False)
    monkeypatch.setattr(otel, "_tracer", None)
    monkeypatch.setenv("KAKVEDA_OTEL_ENABLED", "1")
    ok = otel.setup_otel("test")
    try:
        import opentelemetry.sdk  # noqa: F401

        assert ok is True and otel.get_tracer() is not None
    except ImportError:
        # SDK absent: enabling must degrade to a no-op, never crash.
        assert ok is False and otel.get_tracer() is None
    # middleware wraps a handler without breaking it
    from aiohttp import web

    async def go():
        app = web.Application(middlewares=[otel.otel_middleware()])

        async def hello(request):
            return web.json_response({"ok": True})

        app.router.add_get("/", hello)
        client = await _client(app)
        try:
            r = await client.get("/")
            assert r.status == 200 and (await r.json())["ok"]
        finally:
            await client.close()

    run(go())
    monkeypatch.setattr(otel, "_setup_done", False)
    monkeypatch.setattr(otel, "_tracer", None)
