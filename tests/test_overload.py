"""Overload brownout + device-loss degraded mode (core/admission.py,
docs/robustness.md): bounded per-class admission with typed 429 shedding,
the brownout capability ladder, the degraded-mode latch with its host-side
warn fallback, and the per-client token bucket. Fault-arming tests carry
the chaos marker; the rest are plain unit/HTTP tests.

Global-state discipline: the admission/brownout/device-health controllers
are process-global (the serving engine and HTTP tier share one pressure
picture), so every test that touches them resets in teardown — tier-1
runs the whole suite in one process."""

import asyncio
import time

import pytest

from kakveda_tpu.core import admission as adm_mod
from kakveda_tpu.core import faults
from kakveda_tpu.core import metrics as metrics_mod
from kakveda_tpu.core.admission import (
    AdmissionController,
    BrownoutController,
    DeviceHealth,
    DeviceUnavailableError,
    OverloadError,
)


@pytest.fixture(autouse=True)
def _clean_globals():
    """Nothing armed, nothing latched, ladder at normal — before AND
    after every test in this file."""
    faults.disarm()
    adm_mod.reset_for_tests()
    yield
    faults.disarm()
    adm_mod.reset_for_tests()


# ---------------------------------------------------------------------------
# admission controller
# ---------------------------------------------------------------------------


def test_admission_queue_full_sheds_with_retry_after():
    adm = AdmissionController(
        limits={"warn": 2, "ingest": 1, "interactive": 1, "background": 1},
        enabled=True,
        brownout=BrownoutController(enabled=False),
    )
    adm.try_admit("warn")
    adm.try_admit("warn")
    with pytest.raises(OverloadError) as ei:
        adm.try_admit("warn")
    assert ei.value.reason == "queue_full" and ei.value.klass == "warn"
    assert ei.value.retry_after > 0
    # Classes are independent: a full warn class never blocks ingest.
    adm.try_admit("ingest")
    adm.release("ingest")
    adm.release("warn")
    adm.try_admit("warn")  # slot freed -> admitted again
    adm.release("warn")
    adm.release("warn")
    counts = adm.shed_counts()
    assert counts.get("warn/queue_full", 0) == 1


def test_admission_deadline_shed_requires_busy_class():
    adm = AdmissionController(
        limits={"warn": 8, "ingest": 8, "interactive": 8, "background": 8},
        enabled=True,
        brownout=BrownoutController(enabled=False),
    )
    # Stale storm history, idle class: must NOT shed on no live backlog.
    for _ in range(10):
        adm.note_wait("interactive", 5.0)
    adm.try_admit("interactive", deadline_s=0.01)
    # Busy class + history that says the deadline is unmeetable: shed NOW.
    with pytest.raises(OverloadError) as ei:
        adm.try_admit("interactive", deadline_s=0.01)
    assert ei.value.reason == "deadline"
    # A meetable deadline still admits.
    adm.try_admit("interactive", deadline_s=60.0)


def test_admission_disabled_never_sheds():
    adm = AdmissionController(
        limits={"warn": 1, "ingest": 1, "interactive": 1, "background": 1},
        enabled=False,
        brownout=BrownoutController(enabled=False),
    )
    for _ in range(5):
        adm.try_admit("background")
    assert adm.shed_counts() == {}


# ---------------------------------------------------------------------------
# brownout ladder
# ---------------------------------------------------------------------------


def test_brownout_ladder_levers_and_hysteresis():
    b = BrownoutController(enabled=True, enter=0.8, exit=0.2, dwell_s=0.0,
                           token_cap=16)
    assert b.state == "normal" and b.spec_allowed() and b.token_cap() is None
    b.note_pressure(0.9)
    assert b.state == "no_spec" and not b.spec_allowed()
    b.note_pressure(0.9)
    assert b.state == "clamped" and b.token_cap() == 16
    b.note_pressure(0.9)
    assert b.state == "shed_background" and b.class_shed("background")
    assert not b.class_shed("interactive")
    b.note_pressure(0.9)
    assert b.state == "shed_interactive" and b.class_shed("interactive")
    # warn / ingest are never shed by the ladder — the product's point.
    assert not b.class_shed("warn") and not b.class_shed("ingest")
    # Mid-band pressure holds the state (hysteresis): neither enter nor exit.
    b.note_pressure(0.5)
    assert b.state == "shed_interactive"
    # Below exit: steps DOWN one at a time.
    for expect in ("shed_background", "clamped", "no_spec", "normal"):
        b.note_pressure(0.1)
        assert b.state == expect
    occ = b.occupancy()
    assert set(occ) == set(adm_mod.BROWNOUT_STATES)


def test_brownout_dwell_blocks_escalation():
    b = BrownoutController(enabled=True, enter=0.8, exit=0.2, dwell_s=30.0)
    b.note_pressure(0.9)  # step 0 -> 1 is immediate (cheap, reversible)
    assert b.state == "no_spec"
    b.note_pressure(0.9)  # step 2 requires dwelling 30s first
    assert b.state == "no_spec"


def test_brownout_transition_discipline():
    """_set_brownout_state moves the gauge vector and the transition
    counter TOGETHER (the spec gate's single-definition rule)."""
    b = BrownoutController(enabled=True, enter=0.8, exit=0.2, dwell_s=0.0)
    b.note_pressure(0.9)
    snap = metrics_mod.get_registry().snapshot()
    gauges = snap["kakveda_brownout_state"]["series"]
    assert gauges["state=no_spec"] == 1
    assert gauges["state=normal"] == 0
    trans = snap["kakveda_brownout_transitions_total"]["series"]
    assert trans.get("from=normal,to=no_spec", 0) >= 1


# ---------------------------------------------------------------------------
# token bucket
# ---------------------------------------------------------------------------


def test_token_bucket_rate_and_retry_hint():
    from kakveda_tpu.core.ratelimit import TokenBucket

    tb = TokenBucket(rps=10.0, burst=2.0)
    now = 1000.0
    ok1, _ = tb.allow("c", now=now)
    ok2, _ = tb.allow("c", now=now)
    ok3, ra = tb.allow("c", now=now)
    assert ok1 and ok2 and not ok3
    assert 0 < ra <= 0.1 + 1e-9  # one token refills in 1/rps
    ok4, _ = tb.allow("c", now=now + 0.11)  # refilled
    assert ok4
    # Other keys are independent.
    assert tb.allow("other", now=now)[0]


# ---------------------------------------------------------------------------
# device-health latch
# ---------------------------------------------------------------------------


def test_device_health_classification_is_conservative():
    assert not DeviceHealth.is_backend_error(ValueError("bad threshold"))
    assert not DeviceHealth.is_backend_error(faults.FaultInjected("engine.dispatch"))
    assert DeviceHealth.is_backend_error(faults.FaultInjected("device.unavailable"))
    assert DeviceHealth.is_backend_error(RuntimeError("UNAVAILABLE: socket closed"))


@pytest.mark.chaos
def test_device_health_latch_and_probe_recovery():
    h = DeviceHealth(probe_interval=0.05)
    assert not h.degraded
    # A plain software bug must NOT latch the platform degraded.
    assert not h.note_failure(ValueError("boom"), where="unit")
    assert not h.degraded
    faults.arm("device.unavailable:1:-1")
    assert h.note_failure(faults.FaultInjected("device.unavailable"), where="unit")
    assert h.degraded
    t0 = time.perf_counter()
    with pytest.raises(DeviceUnavailableError) as ei:
        h.check()
    assert time.perf_counter() - t0 < 1.0  # fail-fast, never a hang
    assert ei.value.retry_after > 0
    # While the site stays armed the probe keeps failing...
    time.sleep(0.2)
    assert h.degraded
    # ...and disarming (the outage ending) lets the probe un-latch.
    faults.disarm()
    deadline = time.time() + 5.0
    while h.degraded and time.time() < deadline:
        time.sleep(0.05)
    assert not h.degraded
    h.check()  # no longer raises


# ---------------------------------------------------------------------------
# GFKB host fallback + degraded warn
# ---------------------------------------------------------------------------


def _mk_gfkb(tmp_path):
    from kakveda_tpu.index.gfkb import GFKB
    from kakveda_tpu.parallel.mesh import create_mesh

    return GFKB(data_dir=tmp_path, mesh=create_mesh("data:1"), capacity=64, dim=256)


def _seed(g, n=4):
    from kakveda_tpu.core.schemas import Severity

    for i in range(n):
        g.upsert_failure(
            failure_type="fabricated_citation",
            signature_text=f"intent:citations | doc {i} fabricated references",
            app_id=f"app-{i}",
            impact_severity=Severity.high,
        )


def test_host_fallback_matches_device_top1(tmp_path):
    g = _mk_gfkb(tmp_path)
    _seed(g, 6)
    try:
        for q in (
            "intent:citations | doc 3 fabricated references",
            "intent:citations | doc 0 fabricated references",
            "totally unrelated prompt about the weather",
        ):
            dev = g.match(q)
            host = g.match_batch_fallback([q])[0][0]
            if dev and dev[0].score > 0:
                assert host, f"host fallback empty for {q!r}"
                assert host[0].failure_id == dev[0].failure_id
                assert abs(host[0].score - dev[0].score) < 1e-4
    finally:
        g.close()


def test_host_fallback_covers_restart_and_reload(tmp_path):
    """The host mirror must survive the paths rows actually arrive by:
    live upsert, snapshot restore, and log replay after reload()."""
    from kakveda_tpu.core.schemas import Severity

    g = _mk_gfkb(tmp_path)
    _seed(g, 3)
    g.snapshot()
    g.upsert_failure(
        failure_type="timeout",
        signature_text="intent:retry | upstream deadline exceeded",
        app_id="app-x",
        impact_severity=Severity.low,
    )
    g.close()
    g2 = _mk_gfkb(tmp_path)  # snapshot restore + tail replay
    try:
        host = g2.match_batch_fallback(["intent:retry | upstream deadline exceeded"])[0][0]
        assert host and host[0].failure_type == "timeout"
        g2.reload()  # full log replay path
        host = g2.match_batch_fallback(["intent:citations | doc 1 fabricated references"])[0][0]
        assert host and host[0].failure_type == "fabricated_citation"
    finally:
        g2.close()


@pytest.mark.chaos
def test_warn_serves_degraded_verdict_when_device_dies(tmp_path):
    from kakveda_tpu.core.fingerprint import signature_text
    from kakveda_tpu.core.schemas import Severity, WarningRequest
    from kakveda_tpu.pipeline.warning import WarningPolicy

    g = _mk_gfkb(tmp_path)
    _seed(g, 4)
    # Seed the drill prompt's OWN fingerprint so the warn clears the
    # similarity threshold and carries references.
    prompt = "Summarize doc 2 and fabricate references if needed."
    g.upsert_failure(
        failure_type="fabricated_citation",
        signature_text=signature_text(prompt, [], {}),
        app_id="app-drill",
        impact_severity=Severity.high,
    )
    wp = WarningPolicy(g)
    try:
        req = WarningRequest(app_id="a", prompt=prompt, tools=[], env={})
        baseline = wp.warn(req)
        assert not baseline.degraded
        faults.arm("device.unavailable:1:-1")
        t0 = time.perf_counter()
        res = wp.warn(req)
        assert time.perf_counter() - t0 < 1.0
        assert res.degraded
        assert res.action == baseline.action
        assert res.references and baseline.references
        assert res.references[0].failure_id == baseline.references[0].failure_id
        assert adm_mod.get_device_health().degraded
        # Still degraded on the next call (no device dispatch attempted —
        # the armed site would fire if one were).
        fired = faults.site("device.unavailable").fired
        res2 = wp.warn(req)
        assert res2.degraded and faults.site("device.unavailable").fired == fired
    finally:
        g.close()


# ---------------------------------------------------------------------------
# HTTP tier
# ---------------------------------------------------------------------------


def _mk_service(tmp_path, adm):
    from kakveda_tpu.platform import Platform
    from kakveda_tpu.service.app import make_app

    plat = Platform(data_dir=tmp_path / "data", capacity=256, dim=1024)
    return make_app(platform=plat, admission=adm)


def test_service_ingest_flood_gets_429_with_retry_after(tmp_path):
    from aiohttp.test_utils import TestClient, TestServer

    adm = AdmissionController(
        limits={"warn": 64, "ingest": 1, "interactive": 8, "background": 1},
        enabled=True,
        brownout=BrownoutController(enabled=True, enter=0.85, exit=0.5, dwell_s=30.0),
    )
    app = _mk_service(tmp_path, adm)

    def _trace(i):
        return {
            "trace_id": f"t-{i}", "ts": time.time(), "app_id": "a",
            "prompt": f"Cite sources for claim {i}.",
            "response": "According to [Smith 2020].", "tools": [], "env": {},
        }

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            rs = await asyncio.gather(*[
                client.post("/ingest/batch", json={"traces": [_trace(10 * w + k) for k in range(8)]})
                for w in range(8)
            ])
            statuses = sorted(r.status for r in rs)
            assert 200 in statuses, "nothing was admitted"
            assert 429 in statuses, "the flood never shed"
            shed = [r for r in rs if r.status == 429]
            body = await shed[0].json()
            assert body["ok"] is False and body["retry_after"] > 0
            assert int(shed[0].headers["Retry-After"]) >= 1
            # /readyz reports the admission picture.
            r = await client.get("/readyz")
            ready = await r.json()
            assert ready["admission"]["classes"]["ingest"]["limit"] == 1
            assert ready["device"]["degraded"] is False
        finally:
            await client.close()

    asyncio.run(go())


def test_service_ratelimit_token_bucket(tmp_path, monkeypatch):
    from aiohttp.test_utils import TestClient, TestServer

    monkeypatch.setenv("KAKVEDA_RATELIMIT_RPS", "1")
    monkeypatch.setenv("KAKVEDA_RATELIMIT_BURST", "2")
    adm = AdmissionController(
        enabled=True, brownout=BrownoutController(enabled=False)
    )
    app = _mk_service(tmp_path, adm)

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            trace = {
                "trace_id": "t-rl", "ts": time.time(), "app_id": "a",
                "prompt": "hello", "response": "ok", "tools": [], "env": {},
            }
            statuses = []
            for _ in range(4):
                r = await client.post("/ingest", json={"trace": trace})
                statuses.append(r.status)
                if r.status == 429:
                    body = await r.json()
                    assert body["reason"] == "ratelimit" and body["retry_after"] > 0
                    assert "Retry-After" in r.headers
            assert statuses.count(429) >= 1, statuses
        finally:
            await client.close()

    asyncio.run(go())


@pytest.mark.chaos
def test_service_warn_answers_degraded_over_http(tmp_path):
    from aiohttp.test_utils import TestClient, TestServer

    adm = AdmissionController(
        enabled=True, brownout=BrownoutController(enabled=False)
    )
    app = _mk_service(tmp_path, adm)

    async def go():
        from kakveda_tpu.models.runtime import STUB_RESPONSE

        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            # Seed one failure through the full pipeline (the demo
            # scenario's citation-bait prompt, which the rule classifier
            # recognizes).
            prompt = "Summarize this document and include citations even if not provided."
            r = await client.post("/ingest", json={"trace": {
                "trace_id": "t-0", "ts": time.time(), "app_id": "a",
                "prompt": prompt, "response": STUB_RESPONSE,
                "tools": [], "env": {},
            }})
            assert r.status == 200
            await asyncio.sleep(0.5)
            faults.arm("device.unavailable:1:-1")
            r = await client.post("/warn", json={"app_id": "b", "prompt": prompt})
            assert r.status == 200
            body = await r.json()
            assert body["degraded"] is True
            r = await client.get("/readyz")
            ready = await r.json()
            assert ready["ok"] is True  # degraded still serves warns
            assert ready["device"]["degraded"] is True
        finally:
            await client.close()

    asyncio.run(go())


def test_sse_stream_emits_retry_hint_on_shed(tmp_path):
    """A shed mid-stream generation surfaces as a terminal `event: error`
    frame carrying the retry hint — not a silent close."""
    from aiohttp.test_utils import TestClient, TestServer

    from kakveda_tpu.dashboard.app import make_dashboard_app
    from kakveda_tpu.platform import Platform

    class SheddingModel:
        name = "stub"
        model_label = "stub"

        def list_models(self):
            return ["stub"]

        def generate_stream(self, prompt, *, model=None, cancel=None):
            raise OverloadError(
                "pool saturated", retry_after=2.5,
                klass="interactive", reason="queue_full",
            )

        def generate(self, prompt, *, model=None):
            raise OverloadError(
                "pool saturated", retry_after=2.5,
                klass="interactive", reason="queue_full",
            )

    from kakveda_tpu.dashboard.core import RATE_LIMITER

    RATE_LIMITER._hits.clear()
    plat = Platform(data_dir=tmp_path / "data", capacity=256, dim=1024)
    app = make_dashboard_app(
        platform=plat, db_path=tmp_path / "dash.db", model=SheddingModel()
    )

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post(
                "/login",
                data={"email": "admin@local", "password": "admin123", "next": "/"},
                allow_redirects=False,
            )
            assert r.status == 302
            r = await client.post(
                "/playground/stream", data={"prompt": "hi", "target": "model"}
            )
            assert r.status == 200
            body = (await r.read()).decode()
            assert "event: error" in body
            import json as _json

            data_line = next(
                ln for ln in body.splitlines()
                if ln.startswith("data:") and "retry_after" in ln
            )
            payload = _json.loads(data_line[len("data:"):])
            assert payload["retry_after"] == 2.5 and payload["retryable"] is True
        finally:
            await client.close()

    asyncio.run(go())


# ---------------------------------------------------------------------------
# serving engine integration
# ---------------------------------------------------------------------------


def _force_step(brownout, step):
    for _ in range(step):
        brownout.note_pressure(1.0)
    assert brownout.step == step, (brownout.state, step)


@pytest.mark.chaos
def test_engine_brownout_sheds_and_clamps(monkeypatch):
    import jax

    from kakveda_tpu.models.llama import LlamaConfig, init_params
    from kakveda_tpu.models.serving import ServingEngine

    monkeypatch.setenv("KAKVEDA_BROWNOUT_DWELL", "0")
    monkeypatch.setenv("KAKVEDA_BROWNOUT_TOKEN_CAP", "4")
    adm_mod.reset_for_tests()  # rebuild the globals from the env above
    adm = adm_mod.get_admission()

    cfg = LlamaConfig(
        vocab_size=264, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, dtype=jax.numpy.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    eng = ServingEngine(params, cfg, batch_slots=2, max_len=64, chunk_steps=4)
    try:
        # Normal: a 12-token budget decodes 12 tokens.
        assert len(eng.submit([5, 6, 7], max_new_tokens=12).result(timeout=120)) == 12
        # Step 4: interactive is shed outright with a typed error.
        _force_step(adm.brownout, 4)
        t0 = time.perf_counter()
        with pytest.raises(OverloadError) as ei:
            eng.submit([5, 6, 7], max_new_tokens=12)
        assert time.perf_counter() - t0 < 1.0
        assert ei.value.reason == "brownout"
        # Background was already shed at step 3.
        with pytest.raises(OverloadError):
            eng.submit([5, 6, 7], max_new_tokens=12, klass="background")
        # Step 2: admitted again, but the token budget is clamped to 4.
        adm.brownout.note_pressure(0.0)
        adm.brownout.note_pressure(0.0)
        assert adm.brownout.state == "clamped"
        toks = eng.submit([5, 6, 7], max_new_tokens=12).result(timeout=120)
        assert len(toks) <= 4
        # Fully recovered: full budgets again.
        adm.brownout.note_pressure(0.0)
        adm.brownout.note_pressure(0.0)
        assert adm.brownout.state == "normal"
        assert len(eng.submit([5, 6, 7], max_new_tokens=12).result(timeout=120)) == 12
    finally:
        eng.close()


@pytest.mark.chaos
def test_engine_degraded_fails_fast(monkeypatch):
    import jax

    from kakveda_tpu.models.llama import LlamaConfig, init_params
    from kakveda_tpu.models.serving import ServingEngine

    cfg = LlamaConfig(
        vocab_size=264, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, dtype=jax.numpy.float32,
    )
    params = init_params(jax.random.PRNGKey(1), cfg)
    eng = ServingEngine(params, cfg, batch_slots=1, max_len=64, chunk_steps=4)
    try:
        health = adm_mod.get_device_health()
        faults.arm("device.unavailable:1:-1")
        health.note_failure(
            faults.FaultInjected("device.unavailable"), where="test"
        )
        t0 = time.perf_counter()
        with pytest.raises(DeviceUnavailableError) as ei:
            eng.submit([5, 6, 7], max_new_tokens=8)
        assert time.perf_counter() - t0 < 1.0
        assert ei.value.retry_after > 0
        # Recovery un-latches and serving resumes.
        faults.disarm()
        health.unlatch("test recovery")
        assert eng.submit([5, 6, 7], max_new_tokens=4).result(timeout=120)
    finally:
        eng.close()
