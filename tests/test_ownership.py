"""Sharded-ownership GFKB tests (fleet/ownership.py, docs/scale-out.md):
placement determinism and R-scoping, exact arc/coverage accounting,
scoped replication publish, scatter-gather top-k merge + partial-result
contract, the ownership-epoch fence (incl. DLQ replay to a migrated
range), applied-log compaction, router-verdict liveness unification, and
the rebalance-under-storm chaos drill over real subprocess replicas."""

import asyncio
import dataclasses
import json
import time
import uuid
from datetime import datetime, timezone

import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from kakveda_tpu.core import faults
from kakveda_tpu.fleet.ownership import (
    MigrationError,
    OwnershipState,
    OwnershipView,
    parse_members,
    plan_targets,
    responsible_source,
    shard_key_of_row,
)


def run(coro):
    return asyncio.run(coro)


def _members(n):
    return {f"r{i}": f"http://127.0.0.1:{7000 + i}" for i in range(n)}


def _rows(n, tag, app_of=lambda i: f"app-{i % 4}"):
    return [
        {
            "failure_type": "TIMEOUT",
            "signature_text": f"{tag} timeout calling service {i}",
            "app_id": app_of(i),
            "impact_severity": "medium",
            "context_signature": {},
            "root_cause": None,
            "resolution": None,
        }
        for i in range(n)
    ]


# ---------------------------------------------------------------------------
# placement: determinism, R-scoping, arcs, coverage holes
# ---------------------------------------------------------------------------


def test_view_holders_deterministic_and_r_scoped():
    """Placement is a pure function of (members, R): two independently
    built views agree on every key, holders are exactly R distinct
    members led by the owner, and roles are consistent with the walk."""
    a = OwnershipView(_members(4), replication=2)
    b = OwnershipView(dict(reversed(list(_members(4).items()))), replication=2)
    for i in range(300):
        k = f"app-{i}"
        h = a.holders(k)
        assert h == b.holders(k)
        assert len(h) == 2 and len(set(h)) == 2
        assert a.owner(k) == h[0]
        assert a.role(h[0], k) == "owner"
        assert a.role(h[1], k) == "standby"
        assert a.role("r-not-a-member", k) is None
        assert a.is_holder(h[0], k) and a.is_holder(h[1], k)


def test_view_replication_clamped_to_membership():
    v = OwnershipView(_members(2), replication=5)
    assert v.replication == 2  # R can never exceed the member count
    solo = OwnershipView({"r0": ""}, replication=3)
    assert solo.replication == 1 and solo.holders("k") == ["r0"]


def test_view_arc_accounting_and_coverage_holes():
    """Arc accounting is exact: every vnode arc carries an R-tuple, owned
    counts sum to the arc total, and a coverage hole exists IFF an arc
    lost its entire holder set."""
    v = OwnershipView(_members(4), replication=2)
    arcs = v.arcs()
    assert arcs and all(len(a) == 2 for a in arcs)
    assert sum(v.arc_counts(r)[0] for r in v.members) == len(arcs)
    # Healthy fleet: zero holes. One member down with R=2: still zero.
    assert v.coverage_holes(v.members) == 0
    assert v.coverage_holes(["r0", "r1", "r2"]) == 0
    # A single survivor cannot cover arcs held by the other three.
    assert v.coverage_holes(["r0"]) > 0
    assert v.coverage_holes([]) == len(arcs)


def test_view_epoch_serialization_and_persistence(tmp_path):
    v = OwnershipView(_members(3), replication=2, epoch=4)
    assert v.with_epoch(9).epoch == 9
    grown = v.with_members({**_members(3), "r3": "http://127.0.0.1:7003"})
    assert grown.epoch == 5  # membership change bumps by default
    rt = OwnershipView.from_dict(v.to_dict())
    assert rt.epoch == 4 and rt.members == v.members
    assert rt.holders("app-17") == v.holders("app-17")
    p = tmp_path / "ownership.json"
    grown.save(p)
    back = OwnershipView.load(p)
    assert back is not None and back.epoch == 5 and "r3" in back.members
    assert OwnershipView.load(tmp_path / "missing.json") is None
    p.write_text("{not json")
    assert OwnershipView.load(p) is None  # corrupt view: rebuild, not crash


def test_parse_members_and_shard_key():
    assert parse_members("r0=http://h:1, r1=http://h:2/,,bad") == {
        "r0": "http://h:1", "r1": "http://h:2",
    }
    assert parse_members("") == {}
    assert shard_key_of_row({"app_id": "a", "signature_text": "s"}) == "a"
    assert shard_key_of_row({"app_id": "", "signature_text": "s"}) == "s"
    assert shard_key_of_row({}) == ""


def test_rebalance_plan_is_bounded_and_single_sourced():
    """Adding one member moves only the keys it gains (bounded movement),
    each shipped by exactly one responsible source — the first surviving
    OLD holder, so R-way replication guarantees it has the rows."""
    old = OwnershipView(_members(3), replication=2)
    new = old.with_members({**_members(3), "r3": "http://127.0.0.1:7003"})
    keys = [f"app-{i}" for i in range(500)]
    moved = 0
    for k in keys:
        targets = plan_targets(k, old, new)
        assert set(targets) <= {"r3"}  # only the newcomer gains ranges
        if targets:
            moved += 1
            src = responsible_source(k, old, sorted(old.members))
            assert src in old.holders(k)
    # ~R/N of keys gain a holder on scale-out 3 -> 4; generous slack.
    assert 0.05 < moved / len(keys) < 0.75, moved
    # A dead source is skipped; no surviving holder -> None.
    k = keys[0]
    h = old.holders(k)
    assert responsible_source(k, old, [h[1]]) == h[1]
    assert responsible_source(k, old, []) is None


def test_run_rebalance_rejects_non_monotonic_epoch():
    old = OwnershipView(_members(2), replication=2, epoch=3)
    from kakveda_tpu.fleet.ownership import run_rebalance

    with pytest.raises(ValueError):
        run_rebalance(old, old.with_epoch(3))
    with pytest.raises(MigrationError) as ei:
        run_rebalance(
            old, OwnershipView({"rX": "http://h:1"}, replication=1, epoch=4)
        )
    assert ei.value.flipped is False  # nothing changed; retry is safe


# ---------------------------------------------------------------------------
# scoped replication publish (platform.replicate_rows)
# ---------------------------------------------------------------------------


def test_replicate_rows_scoped_to_holders(tmp_path):
    """Under ownership each row is published ONLY to the holders of its
    shard key (minus self) on per-destination topics — never on the
    legacy broadcast topic — and scoped events carry the epoch."""
    from kakveda_tpu.events.bus import TOPIC_GFKB_REPLICATE, replicate_topic
    from kakveda_tpu.platform import Platform

    plat = Platform(data_dir=tmp_path / "a", capacity=128, dim=512)
    view = OwnershipView(_members(3), replication=2, epoch=7)
    plat.replica_id = "r0"
    plat.ownership = OwnershipState(view, "r0")

    got = {}
    for rid in view.members:
        plat.bus.subscribe(
            replicate_topic(rid),
            (lambda r: lambda ev: got.setdefault(r, []).append(ev))(rid),
        )
    broadcast = []
    plat.bus.subscribe(TOPIC_GFKB_REPLICATE, broadcast.append)

    rows = _rows(24, "scoped", app_of=lambda i: f"app-{i % 8}")
    run(plat.replicate_rows(rows))

    assert not broadcast  # never the legacy broadcast under ownership
    assert "r0" not in got  # never to self
    seen = {}
    for rid, evs in got.items():
        for ev in evs:
            assert ev["epoch"] == 7 and ev["origin"] == "r0" and ev["id"]
            for row in ev["rows"]:
                assert view.is_holder(rid, shard_key_of_row(row))
                seen.setdefault(rid, []).append(row["signature_text"])
    # Every row reached every non-self holder of its key — exactly once.
    for row in rows:
        want = [r for r in view.holders(shard_key_of_row(row)) if r != "r0"]
        for rid in want:
            assert seen[rid].count(row["signature_text"]) == 1


def test_replicate_rows_legacy_broadcast_unchanged(tmp_path):
    """KAKVEDA_FLEET_OWNERSHIP off (ownership None): one broadcast event
    on gfkb.replicate with ALL rows — the bit-for-bit legacy contract."""
    from kakveda_tpu.events.bus import TOPIC_GFKB_REPLICATE
    from kakveda_tpu.platform import Platform

    plat = Platform(data_dir=tmp_path / "a", capacity=128, dim=512)
    assert plat.ownership is None
    broadcast = []
    plat.bus.subscribe(TOPIC_GFKB_REPLICATE, broadcast.append)
    rows = _rows(5, "legacy")
    run(plat.replicate_rows(rows))
    assert len(broadcast) == 1
    assert broadcast[0]["rows"] == rows
    assert "epoch" not in broadcast[0]


# ---------------------------------------------------------------------------
# scatter-gather merge + partial contract
# ---------------------------------------------------------------------------


def _merge_answers(scores_by_shard):
    return {
        rid: {
            "ok": True,
            "warning": bool(scores),
            "confidence": max(scores, default=0.1),
            "degraded": False,
            "references": [
                {"failure_id": f"{rid}-{i}", "score": s}
                for i, s in enumerate(scores)
            ],
        }
        for rid, scores in scores_by_shard.items()
    }


def test_merge_warn_global_topk_parity():
    """The merged top-k is exactly the k best of the union of per-shard
    top-ks (absolute scores), each reference tagged with its shard, and
    the verdict body comes from the shard holding the best reference."""
    from kakveda_tpu.fleet.router import _merge_warn

    out = _merge_warn(_merge_answers({"r0": [0.9, 0.4], "r1": [0.8, 0.7]}))
    assert [r["score"] for r in out["references"]] == [0.9, 0.8]
    assert [r["shard"] for r in out["references"]] == ["r0", "r1"]
    assert out["confidence"] == 0.9  # winning shard's own verdict body
    # No shard matched: keep the most confident verdict, empty refs.
    out = _merge_warn(_merge_answers({"r0": [], "r1": []}))
    assert out["references"] == [] and out["ok"]


def test_merge_matches_topk():
    from kakveda_tpu.fleet.router import _merge_matches

    answered = {
        "r0": {"ok": True, "matches": [{"failure_id": "a", "score": 0.5}]},
        "r1": {"ok": True, "matches": [{"failure_id": "b", "score": 0.6}]},
    }
    out = _merge_matches(answered)
    assert [m["failure_id"] for m in out["matches"]] == ["b"]
    assert out["matches"][0]["shard"] == "r1"


def _shard_backend(name, refs=(), status=200, retry_after=None):
    async def warn(request):
        if status != 200:
            headers = {"Retry-After": str(retry_after)} if retry_after else {}
            return web.json_response(
                {"ok": False, "error": "shed"}, status=status, headers=headers
            )
        return web.json_response(
            {"ok": True, "warning": bool(refs), "confidence": 0.5,
             "degraded": False, "served_by": name,
             "references": [
                 {"failure_id": f"{name}-{i}", "score": s}
                 for i, s in enumerate(refs)
             ]},
        )

    async def readyz(request):
        return web.json_response({"ok": True})

    app = web.Application()
    app.add_routes([web.post("/warn", warn), web.get("/readyz", readyz)])
    return app


async def _scatter_fixture(backends_spec, replication):
    """Start stub shards + an ownership router over them; returns
    (router_client, cleanup)."""
    from kakveda_tpu.fleet.router import make_router_app

    clients = []
    urls = {}
    for rid, spec in backends_spec.items():
        c = TestClient(TestServer(_shard_backend(rid, **spec)))
        await c.start_server()
        clients.append(c)
        urls[rid] = str(c.make_url("")).rstrip("/")
    router = make_router_app(
        urls, probe_interval_s=30.0, eject_fails=5, retries=1, timeout_s=5.0,
        ownership=OwnershipView(urls, replication=replication),
    )
    rc = TestClient(TestServer(router))
    await rc.start_server()

    async def cleanup():
        await rc.close()
        for c in clients:
            await c.close()

    return rc, cleanup


def test_scatter_full_coverage_not_partial():
    """Both shards answer: merged verdict is the global top-k with shard
    provenance and partial=false (no arc lost its holders)."""

    async def go():
        rc, cleanup = await _scatter_fixture(
            {"r0": {"refs": (0.9, 0.4)}, "r1": {"refs": (0.8, 0.7)}},
            replication=1,
        )
        try:
            r = await rc.post("/warn", json={"app_id": "app-1", "prompt": "x"})
            body = await r.json()
            assert r.status == 200
            assert body["partial"] is False
            assert "uncovered_ranges" not in body
            assert body["shards"] == {"r0": "ok", "r1": "ok"}
            assert [x["score"] for x in body["references"]] == [0.9, 0.8]
            assert {x["shard"] for x in body["references"]} == {"r0", "r1"}
        finally:
            await cleanup()

    run(go())


@pytest.mark.chaos
def test_scatter_partial_contract_on_shard_loss():
    """Armed gfkb.scatter_gather (count=1): ONE shard sub-request dies
    like a transport error. With R=1 the dead shard's arcs have no other
    holder, so the merged verdict MUST say partial=true with the shard
    marked unreachable — never a silently shrunk full answer, never a
    hang, still HTTP 200 from the surviving coverage."""
    faults.disarm()

    async def go():
        rc, cleanup = await _scatter_fixture(
            {"r0": {"refs": (0.9,)}, "r1": {"refs": (0.8,)}},
            replication=1,
        )
        try:
            faults.arm("gfkb.scatter_gather:1.0:1")
            r = await rc.post("/warn", json={"app_id": "app-1", "prompt": "x"})
            body = await r.json()
            assert r.status == 200
            assert body["partial"] is True
            assert body["uncovered_ranges"] > 0
            assert sorted(body["shards"].values()) == ["ok", "unreachable"]
            assert len(body["references"]) == 1  # surviving shard's answer
        finally:
            faults.disarm()
            await cleanup()

    run(go())


@pytest.mark.chaos
def test_scatter_shard_loss_with_standby_is_not_partial():
    """Same single-shard loss under R=2: the standby holds every arc the
    dead shard owned, so coverage is intact and partial stays false —
    the whole point of R-way range replication."""
    faults.disarm()

    async def go():
        rc, cleanup = await _scatter_fixture(
            {"r0": {"refs": (0.9,)}, "r1": {"refs": (0.8,)}},
            replication=2,
        )
        try:
            faults.arm("gfkb.scatter_gather:1.0:1")
            r = await rc.post("/warn", json={"app_id": "app-1", "prompt": "x"})
            body = await r.json()
            assert r.status == 200 and body["partial"] is False
        finally:
            faults.disarm()
            await cleanup()

    run(go())


def test_scatter_all_shed_stays_typed_429():
    """Every shard shedding: the merge does NOT fabricate a verdict — the
    shed passes through typed (429 + max Retry-After), SHED-NEVER-HANG
    end to end."""

    async def go():
        rc, cleanup = await _scatter_fixture(
            {"r0": {"status": 429, "retry_after": 2},
             "r1": {"status": 429, "retry_after": 5}},
            replication=2,
        )
        try:
            r = await rc.post("/warn", json={"app_id": "app-1", "prompt": "x"})
            assert r.status == 429
            assert r.headers["Retry-After"] == "5"
            body = await r.json()
            assert set(body["shards"].values()) == {"shed"}
        finally:
            await cleanup()

    run(go())


# ---------------------------------------------------------------------------
# service tier: epoch fence, monotonic view swap, DLQ replay to a
# migrated range (the satellite regression)
# ---------------------------------------------------------------------------


def _service_app(tmp_path, monkeypatch, members_spec, replication):
    from kakveda_tpu.platform import Platform
    from kakveda_tpu.service.app import make_app

    monkeypatch.setenv("KAKVEDA_REPLICA_ID", "r0")
    monkeypatch.setenv("KAKVEDA_FLEET_OWNERSHIP", "1")
    monkeypatch.setenv("KAKVEDA_FLEET_MEMBERS", members_spec)
    monkeypatch.setenv("KAKVEDA_FLEET_REPLICATION", str(replication))
    monkeypatch.setenv("KAKVEDA_FLEET_GOSSIP_S", "30")
    plat = Platform(data_dir=tmp_path / "r0", capacity=256, dim=512)
    return plat, make_app(platform=plat)


def _key_owned_by(view, rid, avoid=()):
    for i in range(500):
        k = f"app-{i}"
        if view.owner(k) == rid and k not in avoid:
            return k
    raise AssertionError(f"no key owned by {rid}")


def test_ownership_endpoint_monotonic_and_replicate_fence(tmp_path, monkeypatch):
    """/fleet/ownership swaps only forward (stale pushes no-op) and
    persists; /replicate fences stale-epoch rows this replica no longer
    holds — dropped rows ack as a clean 2xx so at-least-once retires."""
    members = "r0=http://127.0.0.1:1,r1=http://127.0.0.1:2"
    plat, app = _service_app(tmp_path, monkeypatch, members, replication=1)
    m2 = parse_members(members)
    v1 = OwnershipView(m2, replication=1, epoch=1)
    ka = _key_owned_by(v1, "r0")
    kb = _key_owned_by(v1, "r1")

    async def go(client):
        r = await client.get("/fleet/ownership")
        body = await r.json()
        assert body["enabled"] and body["epoch"] == 1
        assert set(body["members"]) == {"r0", "r1"}

        # Current-epoch events apply whole (the fence is only for stale).
        row_a = dict(_rows(1, "fence", app_of=lambda _i: ka)[0])
        r = await client.post("/replicate", json={
            "id": "e-base", "epoch": 1, "ts": time.time(), "rows": [row_a]})
        assert (await r.json())["applied"] == 1

        # Forward swap to epoch 3.
        v3 = OwnershipView(m2, replication=1, epoch=3)
        r = await client.post("/fleet/ownership", json=v3.to_dict())
        body = await r.json()
        assert body == {"ok": True, "stale": False, "epoch": 3}
        # Stale push (epoch 2): no-op ack, view stays at 3.
        r = await client.post(
            "/fleet/ownership",
            json=OwnershipView(m2, replication=1, epoch=2).to_dict(),
        )
        assert (await r.json()) == {"ok": True, "stale": True, "epoch": 3}
        assert OwnershipView.load(tmp_path / "r0" / "ownership.json").epoch == 3

        # Stale-epoch event for a range r0 never held: every row fenced,
        # clean 2xx drop.
        row_b = dict(_rows(1, "fence-b", app_of=lambda _i: kb)[0])
        before = plat.gfkb.count
        r = await client.post("/replicate", json={
            "id": "e-stale", "epoch": 1, "ts": time.time(), "rows": [row_b]})
        body = await r.json()
        assert r.status == 200
        assert body["applied"] == 0 and body["dropped"] == 1
        assert body["reason"] == "stale_epoch"
        assert plat.gfkb.count == before

        # Mixed event: held rows apply, foreign rows fence.
        row_a2 = dict(_rows(1, "fence-mix", app_of=lambda _i: ka)[0])
        r = await client.post("/replicate", json={
            "id": "e-mixed", "epoch": 2, "ts": time.time(),
            "rows": [row_a2, dict(row_b)]})
        body = await r.json()
        assert body["applied"] == 1 and body["dropped"] == 1

    async def wrap():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            await go(client)
        finally:
            await client.close()

    run(wrap())


def test_dlq_replay_to_migrated_range_never_unmigrates(tmp_path, monkeypatch):
    """The satellite regression: a gfkb.replicate event recorded before a
    migration is re-delivered (DLQ replay) AFTER the range moved away.
    It must dedup or cleanly drop — never double-insert at the old
    holder, never re-materialize ('un-migrate') the departed range."""
    members = "r0=http://127.0.0.1:1,r1=http://127.0.0.1:2"
    plat, app = _service_app(tmp_path, monkeypatch, members, replication=1)
    m2 = parse_members(members)
    m3 = {**m2, "r2": "http://127.0.0.1:3"}
    v1 = OwnershipView(m2, replication=1, epoch=1)
    v2 = OwnershipView(m3, replication=1, epoch=2)
    # A key r0 held at epoch 1 that MOVES to the newcomer at epoch 2.
    moved = next(
        k for i in range(500)
        for k in [f"app-{i}"]
        if v1.owner(k) == "r0" and v2.owner(k) == "r2"
    )
    kept = next(
        k for i in range(500)
        for k in [f"app-{i}"]
        if v1.owner(k) == "r0" and v2.owner(k) == "r0"
    )

    async def go(client):
        evt = {"id": "evt-premigration", "epoch": 1, "ts": time.time(),
               "rows": [dict(_rows(1, "mig", app_of=lambda _i: moved)[0]),
                        dict(_rows(1, "keep", app_of=lambda _i: kept)[0])]}
        r = await client.post("/replicate", json=evt)
        assert (await r.json())["applied"] == 2
        count = plat.gfkb.count
        occ = {rec.signature_text: rec.occurrences
               for rec in plat.gfkb.list_failures()}

        # The migration flips the view to epoch 2; `moved` now lives on r2.
        r = await client.post("/fleet/ownership", json=v2.to_dict())
        assert (await r.json())["epoch"] == 2

        # DLQ replay of the SAME event: fence keeps only `kept`, whose
        # apply dedups by event id — nothing changes anywhere.
        r = await client.post("/replicate", json=evt)
        body = await r.json()
        assert r.status == 200 and body["applied"] == 0
        assert body["dropped"] == 1
        assert plat.gfkb.count == count
        assert {rec.signature_text: rec.occurrences
                for rec in plat.gfkb.list_failures()} == occ

        # A NEW stale-epoch event for the migrated range: clean drop —
        # re-delivery must never re-grow a range that moved away.
        r = await client.post("/replicate", json={
            "id": "evt-straggler", "epoch": 1, "ts": time.time(),
            "rows": [dict(_rows(1, "mig2", app_of=lambda _i: moved)[0])]})
        body = await r.json()
        assert body["applied"] == 0 and body["reason"] == "stale_epoch"
        assert plat.gfkb.count == count

    async def wrap():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            await go(client)
        finally:
            await client.close()

    run(wrap())


# ---------------------------------------------------------------------------
# applied-log compaction (startup rewrite, bounded dedup tail)
# ---------------------------------------------------------------------------


def test_applied_log_compacts_on_startup(tmp_path, monkeypatch):
    from kakveda_tpu.index.gfkb import GFKB

    monkeypatch.setenv("KAKVEDA_GFKB_APPLIED_MAX", "8")
    kb = GFKB(data_dir=tmp_path / "d", capacity=256, dim=512)
    for i in range(20):
        assert kb.apply_replication(_rows(1, f"ev{i}"), f"evt-{i}") == 1
    kb.close()
    applied = tmp_path / "d" / "applied_events.jsonl"
    assert len(applied.read_text().splitlines()) == 20  # append-only live

    kb2 = GFKB(data_dir=tmp_path / "d", capacity=256, dim=512)
    lines = applied.read_text().splitlines()
    assert len(lines) == 8  # compacted to the retained FIFO tail
    assert json.loads(lines[-1])["id"] == "evt-19"
    # Recent ids still dedup; rows are intact.
    assert kb2.apply_replication(_rows(1, "ev19"), "evt-19") == 0
    assert kb2.count == 20
    kb2.close()


def test_applied_log_compaction_opt_out(tmp_path, monkeypatch):
    from kakveda_tpu.index.gfkb import GFKB

    monkeypatch.setenv("KAKVEDA_GFKB_APPLIED_MAX", "4")
    monkeypatch.setenv("KAKVEDA_GFKB_APPLIED_COMPACT", "0")
    kb = GFKB(data_dir=tmp_path / "d", capacity=64, dim=512)
    for i in range(10):
        kb.apply_replication(_rows(1, f"ev{i}"), f"evt-{i}")
    kb.close()
    kb2 = GFKB(data_dir=tmp_path / "d", capacity=64, dim=512)
    applied = tmp_path / "d" / "applied_events.jsonl"
    assert len(applied.read_text().splitlines()) == 10  # untouched
    kb2.close()


# ---------------------------------------------------------------------------
# one liveness world-view: router verdicts folded into FleetView
# ---------------------------------------------------------------------------


def test_fleetview_router_verdicts_gate_pressure():
    """A peer the router's probe verdict marks dead stops pinning the
    pressure floor immediately (not after its sample's TTL), the router's
    own synthetic sample never counts as occupancy, and per-peer
    ownership epochs surface for doctor's agreement check."""
    from kakveda_tpu.fleet.gossip import FleetView

    fv = FleetView(ttl_s=10.0)
    assert fv.fold({"replica": "rA", "seq": 1, "ts": time.time(),
                    "occupancy": 0.9, "ownership_epoch": 4})
    assert fv.fleet_pressure() == pytest.approx(0.9)
    assert fv.fold({"replica": FleetView.ROUTER_SENDER, "seq": 1,
                    "ts": time.time(), "occupancy": 0.0,
                    "probe_verdicts": {"rA": False}})
    assert fv.probe_verdicts() == {"rA": False}
    assert fv.fleet_pressure() == 0.0  # dead peer skipped, router excluded
    # Verdict flips back: the same sample counts again.
    assert fv.fold({"replica": FleetView.ROUTER_SENDER, "seq": 2,
                    "ts": time.time(), "occupancy": 0.0,
                    "probe_verdicts": {"rA": True}})
    assert fv.fleet_pressure() == pytest.approx(0.9)
    assert fv.ownership_epochs() == {"rA": 4}


# ---------------------------------------------------------------------------
# the rebalance-under-storm chaos drill (real subprocess replicas)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_rebalance_under_storm_drill(tmp_path):
    """ISSUE 13 acceptance drill: a 2-replica ownership fleet (R=2) under
    steady warn traffic scales out to 3 via the range-migration protocol
    (snapshot-ship -> flip -> drain, driven by the router's
    /fleet/rebalance), then an OWNER gets SIGTERM'd mid-storm. Zero lost
    warns, zero hung, zero errors, sheds confined to sheddable classes,
    bounded partial-verdict rate, and the survivors converge on the
    promoted epoch within the gossip TTL."""
    import yaml

    from kakveda_tpu.fleet.router import ROUTER_KEY, make_router_app
    from kakveda_tpu.fleet.supervisor import FleetSupervisor, pick_port_base
    from kakveda_tpu.traffic.replay import run_scenario
    from kakveda_tpu.traffic.scenarios import make_scenario
    from kakveda_tpu.traffic.slo import evaluate

    cfg = tmp_path / "config.yaml"
    cfg.write_text(yaml.safe_dump({
        "failure_matching": {
            "similarity_threshold": 0.8, "embedding_dim": 512, "top_k": 5,
        }
    }))
    sup = FleetSupervisor(
        tmp_path, port_base=pick_port_base(4), replicas=2,
        env={
            "JAX_PLATFORMS": "cpu",
            "KAKVEDA_CONFIG_PATH": str(cfg),
            "KAKVEDA_INDEX_CAPACITY": "1024",
            "KAKVEDA_FLEET_OWNERSHIP": "1",
            "KAKVEDA_FLEET_REPLICATION": "2",
            "KAKVEDA_FLEET_GOSSIP_S": "0.2",
            "KAKVEDA_BUS_RETRIES": "2",
            "KAKVEDA_BUS_RETRY_BASE": "0.01",
            "KAKVEDA_GC_TUNE": "0",
        },
    )
    sc = make_scenario(
        "rebalance_storm", seed=7, duration_s=8.0, warn_rps=10.0, apps=8,
        kill_replica=0, gossip_ttl_s=5.0, max_partial_rate=0.1,
    )
    partials = 0

    def _trace(app_id, i):
        from kakveda_tpu.models.runtime import STUB_RESPONSE

        return {
            "trace_id": str(uuid.uuid4()),
            "ts": datetime.now(timezone.utc).isoformat(),
            "app_id": app_id,
            "agent_id": "agent-1",
            "prompt": f"Cite sources for claim {i} even if unavailable.",
            "response": STUB_RESPONSE,
            "model": "stub", "tools": [], "env": {"os": "linux"},
        }

    async def go():
        nonlocal partials
        import httpx

        router_app = make_router_app(
            sup.backend_map(), probe_interval_s=0.3, eject_fails=2,
            retries=1, timeout_s=10.0,
            ownership=OwnershipView(sup.backend_map(), replication=2),
        )
        rc = TestClient(TestServer(router_app))
        await rc.start_server()
        try:
            # Seed a corpus through the router (keyed ingest; accepted
            # rows replicate range-scoped to their holders).
            for b in range(4):
                r = await rc.post("/ingest/batch", json={
                    "traces": [_trace(f"app-{b * 2 + j % 2}", b * 8 + j)
                               for j in range(6)]})
                assert r.status == 200, await r.text()

            # Pre-spawn the newcomer so the chaos callback only drives
            # the migration protocol (process bring-up is not the drill).
            idx = await asyncio.get_running_loop().run_in_executor(
                None, sup.add_replica)
            await asyncio.get_running_loop().run_in_executor(
                None, sup.wait_ready, 180.0)

            async def post(path, body):
                resp = await rc.post(path, json=body)
                nonlocal partials
                try:
                    data = await resp.json()
                except Exception:
                    data = None
                    await resp.read()
                if isinstance(data, dict) and data.get("partial"):
                    partials += 1
                return resp.status

            async def rebalance_cb(act):
                r = await rc.post("/fleet/rebalance", json={
                    "add": {"id": sup.replica_id(idx), "url": sup.url(idx)}})
                body = await r.json()
                assert r.status == 200 and body["ok"], body
                assert body["epoch"] == 2

            res = await run_scenario(
                sc, post=post, timeout_s=15.0, supervisor=sup,
                callbacks={"rebalance": rebalance_cb},
            )
            res.notes["partial"] = float(partials)

            # Epoch convergence: ejection of the dead owner promotes the
            # view (>= 3) and the push lands on every survivor within the
            # gossip TTL.
            router = router_app[ROUTER_KEY]
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if router.ownership.epoch >= 3 and "r0" in router.ejected():
                    break
                await asyncio.sleep(0.2)
            assert router.ownership.epoch >= 3, router.ownership.epoch
            assert "r0" in router.ejected()
            async with httpx.AsyncClient(timeout=5.0) as hc:
                for i in (1, 2):
                    resp = await hc.get(sup.url(i) + "/fleet/ownership")
                    body = resp.json()
                    assert body["epoch"] >= 3, (i, body)
                    assert set(body["members"]) == {"r0", "r1", "r2"}

            # Survivor coverage is whole: no arc lost all its holders.
            r = await rc.get("/readyz")
            rep = await r.json()
            assert rep["ownership"]["coverage_holes"] == 0
            assert rep["fleet"]["brownout"] == "normal"
            return res
        finally:
            await rc.close()

    try:
        sup.start_all()
        sup.wait_ready(timeout_s=180.0)
        res = run(go())
    finally:
        sup.stop_all()
        faults.disarm()

    # Ladder recovery is measured in-process by the admission handle the
    # drill doesn't have; the router-side brownout check above covers it.
    slo = dataclasses.replace(sc.slo, recovery_s=None)
    report = evaluate(slo, res)
    assert report.ok, report.summary()
    counts = res.class_counts().get("warn", {})
    assert res.generated("warn") > 40
    assert counts.get("ok", 0) == res.generated("warn")  # zero lost, zero
    assert counts.get("shed", 0) == 0                    # shed, zero hung,
    assert counts.get("hung", 0) == 0                    # zero errors
    assert counts.get("error", 0) == 0
