"""Fused Pallas match kernel vs NumPy oracle and the XLA path.

Runs through the Pallas interpreter on the CPU test mesh, so the exact
kernel logic (tiling, masking, iterative top-k, candidate merge) is what's
under test — only the Mosaic lowering differs on real hardware.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from kakveda_tpu.ops import pallas_knn
from kakveda_tpu.ops.knn import ShardedKnn
from kakveda_tpu.parallel.mesh import create_mesh


def _oracle_topk(emb, valid, q, k):
    scores = q.astype(np.float32) @ emb.astype(np.float32).T
    scores = np.where(valid[None, :], scores, -2.0)
    # argsort is stable, so equal scores resolve to the lowest row id.
    order = np.argsort(-scores, axis=1, kind="stable")[:, :k]
    vals = np.take_along_axis(scores, order, axis=1)
    return vals, order


def _rand_index(rows, dim, n_valid, seed=0):
    rng = np.random.default_rng(seed)
    emb = rng.standard_normal((rows, dim)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    valid = np.zeros(rows, bool)
    valid[rng.permutation(rows)[:n_valid]] = True
    q = rng.standard_normal((6, dim)).astype(np.float32)
    q /= np.linalg.norm(q, axis=1, keepdims=True)
    return emb, valid, q


def test_fused_topk_matches_oracle():
    rows, dim, tile = 256, 128, 64
    emb, valid, q = _rand_index(rows, dim, n_valid=200)
    vals, idx = pallas_knn.fused_topk(
        jnp.asarray(emb), jnp.asarray(valid), jnp.asarray(q),
        k=5, row_tile=tile, interpret=True,
    )
    ovals, oidx = _oracle_topk(emb, valid, q, 5)
    np.testing.assert_allclose(np.asarray(vals), ovals, rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(idx), oidx)


def test_fused_topk_ties_and_duplicates():
    # Duplicate rows force exact score ties across different tiles; the
    # kernel must resolve to the lowest row id, like lax.top_k.
    dim, tile = 128, 64
    rng = np.random.default_rng(3)
    base = rng.standard_normal((4, dim)).astype(np.float32)
    base /= np.linalg.norm(base, axis=1, keepdims=True)
    emb = np.tile(base, (32, 1))  # 128 rows: row i is base[i % 4]
    valid = np.ones(128, bool)
    q = base[:2]
    vals, idx = pallas_knn.fused_topk(
        jnp.asarray(emb), jnp.asarray(valid), jnp.asarray(q),
        k=4, row_tile=tile, interpret=True,
    )
    idx = np.asarray(idx)
    # Top-4 for query j are the 4 lowest-id copies of base[j]: j, j+4, j+8, j+12.
    for j in range(2):
        np.testing.assert_array_equal(idx[j], [j, j + 4, j + 8, j + 12])
    assert np.allclose(np.asarray(vals), 1.0, atol=1e-5)


def test_fused_topk_fewer_valid_than_k():
    rows, dim, tile = 128, 128, 64
    emb, valid, q = _rand_index(rows, dim, n_valid=0)
    valid[7] = True
    vals, idx = pallas_knn.fused_topk(
        jnp.asarray(emb), jnp.asarray(valid), jnp.asarray(q),
        k=5, row_tile=tile, interpret=True,
    )
    vals = np.asarray(vals)
    assert np.all(np.asarray(idx)[:, 0] == 7)
    assert np.all(vals[:, 1:] == -2.0), "pad candidates must carry the sentinel"


def test_sharded_knn_pallas_interpret_matches_xla(monkeypatch):
    # The full ShardedKnn path with the Pallas kernel (interpreted) must
    # agree with the plain-XLA path, sharded over the 8-device CPU mesh.
    dim = 128
    monkeypatch.setattr(pallas_knn, "DEFAULT_ROW_TILE", 64)
    mesh = create_mesh("data:-1")
    emb_np = np.random.default_rng(5).standard_normal((300, dim)).astype(np.float32)
    emb_np /= np.linalg.norm(emb_np, axis=1, keepdims=True)
    slots = np.arange(300, dtype=np.int32)
    q = emb_np[:10]

    monkeypatch.setenv("KAKVEDA_PALLAS", "interpret")
    kp = ShardedKnn(mesh, capacity=8 * 64, dim=dim, k=5)
    assert kp.use_pallas
    e, v = kp.alloc()
    e, v = kp.insert(e, v, emb_np, slots)
    pv, pi = kp.topk(e, v, q)

    monkeypatch.setenv("KAKVEDA_PALLAS", "0")
    kx = ShardedKnn(mesh, capacity=8 * 64, dim=dim, k=5)
    assert not kx.use_pallas
    e, v = kx.alloc()
    e, v = kx.insert(e, v, emb_np, slots)
    xv, xi = kx.topk(e, v, q)

    np.testing.assert_allclose(pv, xv, rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(pi, xi)
    assert np.all(pi[:, 0] == np.arange(10)), "self-match must rank first"


def test_supports_layout_gate():
    assert pallas_knn.supports(2048, 256, 1024)
    assert not pallas_knn.supports(1000, 256, 1024)
    assert not pallas_knn.supports(2048, 100, 1024)
    assert not pallas_knn.supports(512, 256, 1024)
