"""Pipeline unit tests: classifier rules, health math, event bus, clustering."""

import asyncio
from datetime import datetime, timezone

import numpy as np
import pytest

from kakveda_tpu.core.schemas import FailureSignal, Severity, TracePayload
from kakveda_tpu.events.bus import EventBus
from kakveda_tpu.models.runtime import STUB_RESPONSE, StubRuntime
from kakveda_tpu.ops.clustering import cluster_embeddings
from kakveda_tpu.pipeline.classifier import HALLUCINATION_CITATION, classify_trace
from kakveda_tpu.pipeline.health_score import HealthScorer


def _trace(prompt, response, app_id="app-A", trace_id="t1"):
    return TracePayload(
        trace_id=trace_id,
        ts=datetime.now(timezone.utc),
        app_id=app_id,
        agent_id="agent-1",
        prompt=prompt,
        response=response,
        model="stub",
        tools=[],
        env={"os": "linux"},
    )


def _failure(app_id="app-A", ftype=HALLUCINATION_CITATION, sev=Severity.medium):
    return FailureSignal(
        trace_id="t",
        ts=datetime.now(timezone.utc),
        app_id=app_id,
        failure_type=ftype,
        severity=sev,
        context_signature={},
    )


class TestClassifier:
    def test_detects_citation_hallucination(self):
        t = _trace("Summarize this and include citations", STUB_RESPONSE)
        sig = classify_trace(t)
        assert sig is not None
        assert sig.failure_type == HALLUCINATION_CITATION
        assert sig.severity == Severity.medium
        assert sig.app_id == "app-A"
        assert sig.context_signature["prompt_shape"].startswith("Summarize")

    def test_no_failure_without_citation_request(self):
        assert classify_trace(_trace("What's 2+2?", STUB_RESPONSE)) is None

    def test_no_failure_without_markers(self):
        assert classify_trace(_trace("Summarize with citations", "I have no sources available.")) is None


class TestHealthScorer:
    def test_first_failure_score(self, tmp_path):
        hs = HealthScorer(tmp_path, persist=True)
        p = hs.on_failure(_failure())
        # base 100 − 3·5 (one medium) − 0 recurrence = 85
        assert p.score == 85.0
        assert p.failure_rate == 0.1
        assert p.recurrent_penalty == 0.0
        assert p.notes["window_failures"] == 1

    def test_recurrence_penalty(self, tmp_path):
        hs = HealthScorer(tmp_path, persist=False)
        hs.on_failure(_failure())
        p = hs.on_failure(_failure())
        # 2 mediums: 100 − 2·3·5 − 1·2.5 = 67.5
        assert p.score == 67.5
        assert p.recurrent_penalty == 2.5
        assert p.avg_recovery_time_sec == 30.0 + 25.0

    def test_score_floor_zero(self, tmp_path):
        hs = HealthScorer(tmp_path, persist=False)
        for _ in range(20):
            p = hs.on_failure(_failure(sev=Severity.high))
        assert p.score == 0.0

    def test_history_persisted(self, tmp_path):
        hs = HealthScorer(tmp_path, persist=True)
        hs.on_failure(_failure(app_id="a1"))
        hs.on_failure(_failure(app_id="a2"))
        hs.on_failure(_failure(app_id="a1"))
        pts = hs.history("a1")
        assert len(pts) == 2
        assert all(p["app_id"] == "a1" for p in pts)


class TestEventBus:
    def test_local_fanout_and_counts(self):
        bus = EventBus()
        got = []

        async def h1(e):
            got.append(("h1", e))

        def h2(e):
            got.append(("h2", e))

        bus.subscribe("t", h1)
        bus.subscribe("t", h2)
        bus.subscribe("t", h2)  # dedupe
        assert bus.topics() == {"t": 2}
        delivered = asyncio.run(bus.publish("t", {"x": 1}))
        assert delivered == 2
        assert len(got) == 2

    def test_publish_no_subscribers(self):
        assert asyncio.run(EventBus().publish("nope", {})) == 0

    def test_failing_subscriber_does_not_break_fanout(self):
        bus = EventBus()
        got = []

        def bad(e):
            raise RuntimeError("boom")

        bus.subscribe("t", bad)
        bus.subscribe("t", lambda e: got.append(e))
        delivered = asyncio.run(bus.publish("t", {"x": 1}))
        assert delivered == 1
        assert got == [{"x": 1}]


class TestClustering:
    def test_two_clear_clusters(self):
        rng = np.random.default_rng(0)
        a = rng.standard_normal(64)
        b = rng.standard_normal(64)
        a /= np.linalg.norm(a)
        b /= np.linalg.norm(b)

        def jitter(v):
            w = v + 0.05 * rng.standard_normal(64)
            return w / np.linalg.norm(w)

        vecs = np.stack([jitter(a), jitter(a), jitter(a), jitter(b), jitter(b)]).astype(np.float32)
        labels = cluster_embeddings(vecs, threshold=0.8)
        assert labels[0] == labels[1] == labels[2]
        assert labels[3] == labels[4]
        assert labels[0] != labels[3]

    def test_isolated_points_get_own_labels(self):
        vecs = np.eye(8, dtype=np.float32)[:4]
        labels = cluster_embeddings(vecs, threshold=0.5)
        assert len(set(labels.tolist())) == 4

    def test_degree_cap_prevents_boilerplate_chaining(self):
        """Boilerplate-heavy corpora put MANY cross-template pairs above
        the threshold; the raw threshold graph transitively chains every
        template into one blob. The union-top-k semantics (shared by both
        tiers) keep each row's edges among its own template when the
        template has > k members — per-template clusters survive."""
        rng = np.random.default_rng(7)
        shared = rng.standard_normal(64)
        shared /= np.linalg.norm(shared)
        n_templates, per = 4, 100  # 100 > _KNN_K: the cap engages
        rows = []
        truth = []
        for t in range(n_templates):
            delta = rng.standard_normal(64)
            c = shared + 0.45 * delta / np.linalg.norm(delta)  # cross-cos ~0.8
            c /= np.linalg.norm(c)
            for _ in range(per):
                w = c + 0.03 * rng.standard_normal(64)  # within-cos ~0.995
                rows.append(w / np.linalg.norm(w))
                truth.append(t)
        vecs = np.stack(rows).astype(np.float32)
        sims = vecs @ vecs.T
        cross = sims[:per, per : 2 * per]
        assert cross.mean() > 0.6, "setup: cross-template sims must clear the threshold"
        labels = cluster_embeddings(vecs, threshold=0.6)
        # purity: majority template per label
        correct = 0
        for lb in set(labels.tolist()):
            member_t = [truth[i] for i in np.flatnonzero(labels == lb)]
            correct += max(member_t.count(t) for t in set(member_t))
        assert correct / len(rows) > 0.99, correct / len(rows)
        assert len(set(labels.tolist())) >= n_templates


def test_stub_runtime_matches_reference_text():
    res = StubRuntime().generate("anything")
    assert res.text == STUB_RESPONSE
    assert res.meta["provider"] == "stub"
    assert "[1]" in res.text  # trips the citation-marker detector


def test_tiered_classifier_llm_judge():
    """LLM tier adds failures for unmarked fabrications, never overrides rule."""
    import time as _time
    from dataclasses import dataclass, field

    from kakveda_tpu.core.schemas import TracePayload
    from kakveda_tpu.models.runtime import GenerateResult, StubRuntime
    from kakveda_tpu.pipeline.classifier import (
        TieredClassifier,
        parse_judge_verdict,
    )

    @dataclass
    class YesJudge:
        name: str = "fake"
        calls: list = field(default_factory=list)

        def generate(self, prompt, *, model=None, max_tokens=256):
            self.calls.append(prompt)
            return GenerateResult(text="YES.", meta={"provider": "fake"})

    def mk(prompt, response):
        return TracePayload(
            trace_id="t", ts=_time.time(), app_id="a", prompt=prompt,
            response=response, tools=[], env={},
        )

    citing_prompt = "Summarize this document and include citations even if not provided."
    marked = mk(citing_prompt, "See references: [1] Smith 2020.")
    unmarked = mk(citing_prompt, "As shown by Smith in his famous 2020 study, things happen.")
    benign = mk("What time is it?", "Noon.")

    judge = YesJudge()
    out = TieredClassifier(runtime=judge).classify_batch([marked, unmarked, benign])
    assert out[0] is not None and "LLM-judged" not in (out[0].root_cause or "")
    assert out[1] is not None and "LLM-judged" in (out[1].root_cause or "")
    assert out[2] is None
    assert len(judge.calls) == 1, "only the ambiguous trace is judged"

    # Stub runtime: canned citations text parses to no verdict -> rule-only.
    assert parse_judge_verdict(StubRuntime().generate("x").text) is None
    out = TieredClassifier(runtime=StubRuntime()).classify_batch([unmarked])
    assert out[0] is None

    assert parse_judge_verdict("no") is False
    assert parse_judge_verdict("Well, YES, clearly") is True


def test_bus_durable_url_subscriptions(tmp_path):
    path = tmp_path / "subs.jsonl"
    bus = EventBus(persist_path=path)
    bus.subscribe("trace.ingested", "http://agent:8120/events")
    bus.subscribe("trace.ingested", "http://other:9000/cb")
    bus.subscribe("failure.detected", "http://agent:8120/events")
    bus.unsubscribe("trace.ingested", "http://other:9000/cb")
    # local callables are never persisted
    bus.subscribe("trace.ingested", lambda e: None)

    bus2 = EventBus(persist_path=path)
    assert bus2.topics() == {"trace.ingested": 1, "failure.detected": 1}
    assert bus2._subs["trace.ingested"] == ["http://agent:8120/events"]

    # torn tail line from a crash mid-append is skipped on replay
    with path.open("a") as f:
        f.write('{"action": "subscribe", "topic": "x", "ur')
    bus3 = EventBus(persist_path=path)
    assert "x" not in bus3.topics()


def test_multihost_config_parsing(monkeypatch):
    from kakveda_tpu.parallel.distributed import multihost_config

    for var in ("KAKVEDA_MULTIHOST", "KAKVEDA_COORDINATOR", "KAKVEDA_NUM_PROCESSES", "KAKVEDA_PROCESS_ID"):
        monkeypatch.delenv(var, raising=False)
    assert multihost_config() is None

    monkeypatch.setenv("KAKVEDA_COORDINATOR", "host0:1234")
    with pytest.raises(ValueError, match="partial multi-host"):
        multihost_config()

    monkeypatch.setenv("KAKVEDA_NUM_PROCESSES", "4")
    monkeypatch.setenv("KAKVEDA_PROCESS_ID", "1")
    explicit = {"coordinator_address": "host0:1234", "num_processes": 4, "process_id": 1}
    assert multihost_config() == explicit

    # flag + complete explicit config: explicit wins over autodetect
    monkeypatch.setenv("KAKVEDA_MULTIHOST", "1")
    assert multihost_config() == explicit
    # kill switch disables even with explicit vars exported
    monkeypatch.setenv("KAKVEDA_MULTIHOST", "0")
    assert multihost_config() is None
    # typo fails loudly
    monkeypatch.setenv("KAKVEDA_MULTIHOST", "yse")
    with pytest.raises(ValueError, match="not understood"):
        multihost_config()

    # autodetect path: flag alone, no explicit vars
    for var in ("KAKVEDA_COORDINATOR", "KAKVEDA_NUM_PROCESSES", "KAKVEDA_PROCESS_ID"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("KAKVEDA_MULTIHOST", "auto")
    assert multihost_config() == {}


def _clustered_points(rng, n_clusters=3, per=40, dim=64, noise=0.05):
    import numpy as np

    centers = rng.normal(size=(n_clusters, dim))
    centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    pts = np.concatenate([centers[i] + noise * rng.normal(size=(per, dim)) for i in range(n_clusters)])
    pts /= np.linalg.norm(pts, axis=1, keepdims=True)
    return pts


def test_knn_graph_clustering_matches_dense():
    import numpy as np

    import kakveda_tpu.ops.clustering as cl

    pts = _clustered_points(np.random.default_rng(0))
    dense = cl.cluster_embeddings(pts, threshold=0.8)

    # force the sparse kNN-graph path with small blocks on the same data
    orig = (cl._DENSE_MAX, cl._BLOCK, cl._QBLOCK)
    cl._DENSE_MAX, cl._BLOCK, cl._QBLOCK = 0, 32, 48
    try:
        cl._block_topk.clear_cache()
        sparse = cl.cluster_embeddings(pts, threshold=0.8)
    finally:
        cl._DENSE_MAX, cl._BLOCK, cl._QBLOCK = orig
        cl._block_topk.clear_cache()

    # identical partitions (labels themselves are smallest-member indices)
    assert (dense == sparse).all()
    assert len(set(dense.tolist())) == 3


def test_knn_graph_projection_rescore_matches_dense():
    """The >131k-row tier (random-projection candidates + exact re-score)
    must reproduce the dense partition on separable data."""
    import numpy as np

    import kakveda_tpu.ops.clustering as cl

    pts = _clustered_points(np.random.default_rng(1), dim=512, per=30)
    dense = cl.cluster_embeddings(pts, threshold=0.8)

    orig = (cl._DENSE_MAX, cl._EXACT_SWEEP_MAX, cl._MINE_DIM)
    cl._DENSE_MAX, cl._EXACT_SWEEP_MAX, cl._MINE_DIM = 0, 0, 64
    try:
        cl._block_topk.clear_cache()
        sparse = cl.cluster_embeddings(pts, threshold=0.8)
    finally:
        cl._DENSE_MAX, cl._EXACT_SWEEP_MAX, cl._MINE_DIM = orig
        cl._block_topk.clear_cache()
    assert (dense == sparse).all()


def test_knn_graph_hub_star_stays_connected():
    """A hub with more above-threshold neighbors than k: spokes still reach
    the hub through THEIR top-k (symmetric union), so the component
    matches the dense threshold graph."""
    import numpy as np

    import kakveda_tpu.ops.clustering as cl

    rng = np.random.default_rng(2)
    hub = rng.normal(size=64)
    hub /= np.linalg.norm(hub)
    # 20 spokes close to the hub; pairwise spoke-spoke sim also high — use
    # tight noise so dense graph is one component.
    pts = np.concatenate([[hub], hub + 0.02 * rng.normal(size=(20, 64))])
    pts /= np.linalg.norm(pts, axis=1, keepdims=True)

    dense = cl.cluster_embeddings(pts, threshold=0.9)
    orig = cl._DENSE_MAX
    cl._DENSE_MAX = 0
    try:
        sparse = cl.cluster_embeddings(pts, threshold=0.9, knn_k=2)
    finally:
        cl._DENSE_MAX = orig
    assert (dense == sparse).all()


def test_tiered_classifier_uses_batch_judging():
    import time as _time
    from dataclasses import dataclass, field

    from kakveda_tpu.core.schemas import TracePayload
    from kakveda_tpu.models.runtime import GenerateResult
    from kakveda_tpu.pipeline.classifier import TieredClassifier

    @dataclass
    class BatchJudge:
        name: str = "fake"
        batch_calls: list = field(default_factory=list)

        def generate(self, prompt, *, model=None, max_tokens=256):
            raise AssertionError("batch path should be used")

        def generate_batch(self, prompts, *, model=None, max_tokens=256):
            self.batch_calls.append(len(prompts))
            return [GenerateResult(text="YES", meta={"provider": "fake"}) for _ in prompts]

    def mk(i):
        return TracePayload(
            trace_id=f"t{i}", ts=_time.time(), app_id="a",
            prompt="Summarize and include citations even if not provided.",
            response=f"Unmarked fabricated study mention {i}.", tools=[], env={},
        )

    judge = BatchJudge()
    out = TieredClassifier(runtime=judge).classify_batch([mk(i) for i in range(5)])
    assert judge.batch_calls == [5], "all ambiguous traces judged in ONE batch"
    assert all(s is not None for s in out)


def test_knn_graph_threshold_zero_ignores_padding():
    import numpy as np

    import kakveda_tpu.ops.clustering as cl

    vecs = np.eye(8, dtype=np.float32)[:5]  # 5 mutually-orthogonal rows
    orig_dense_max = cl._DENSE_MAX
    cl._DENSE_MAX = 0  # force sparse path (pads 5 -> _BLOCK)
    try:
        labels = cl.cluster_embeddings(vecs, threshold=0.0)
    finally:
        cl._DENSE_MAX = orig_dense_max
    # threshold 0 links cos>=0 pairs; orthogonal rows all have cos==0 so
    # they all connect to each other — but via REAL rows (pad rows are
    # masked to -inf and filtered), matching dense
    dense = cl.cluster_embeddings(vecs, threshold=0.0)
    assert (labels == dense).all()


def test_projection_tier_recall_on_separable_data():
    """Random-projection candidate tier (the >131k-row production path,
    forced on here at CI scale): on separable clustered data the projected
    sweep must recover the exact partition — every edge is same-cluster
    (precision 1.0 comes from exact re-scoring) and every cluster stays
    fully connected (recall at the partition level)."""
    import numpy as np

    from kakveda_tpu.ops.clustering import build_knn_edges, cluster_embeddings

    rng = np.random.default_rng(42)
    C, per, dim = 24, 512, 2048  # 12,288 rows, 3 query-block dispatches
    seeds = rng.standard_normal((C, dim)).astype(np.float32)
    seeds /= np.linalg.norm(seeds, axis=1, keepdims=True)
    truth = np.repeat(np.arange(C), per)
    # Noise scaled so its NORM is ~0.3 (0.3/sqrt(dim) per component):
    # within-cluster cosine ~1/1.09≈0.92, cross-cluster ~0 — separable at 0.6.
    noise = (0.3 / np.sqrt(dim)) * rng.standard_normal((C * per, dim)).astype(np.float32)
    vecs = seeds[truth] + noise
    vecs /= np.linalg.norm(vecs, axis=1, keepdims=True)

    rows, cols = build_knn_edges(vecs, threshold=0.6, force_projection=True)
    assert len(rows) > 0
    # Precision: exact re-scoring must kill every cross-cluster candidate.
    assert np.all(truth[rows] == truth[cols])
    # Row-level recall: every row keeps at least one same-cluster edge.
    connected = np.zeros(len(vecs), bool)
    connected[rows] = True
    connected[cols] = True
    assert connected.all()

    labels = cluster_embeddings(vecs, threshold=0.6, force_projection=True)
    # Partition-level recall: each true cluster is one component, and no
    # component spans clusters.
    for c in range(C):
        assert len(np.unique(labels[truth == c])) == 1, f"cluster {c} split"
    assert len(np.unique(labels)) == C
