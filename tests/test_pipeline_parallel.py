"""Pipeline parallelism (models/pipeline.py): GPipe forward parity with the
dense forward, stage sharding placement, microbatch schedules, the MoE
composition, and a pipelined train step that actually reduces the loss."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from kakveda_tpu.models.llama import LlamaConfig, forward, init_params
from kakveda_tpu.models.pipeline import (
    make_pp_train_step,
    place_stacked,
    pp_forward,
    pp_param_specs,
    split_stages,
)
from kakveda_tpu.parallel.mesh import create_mesh

CFG = LlamaConfig(
    vocab_size=64, d_model=32, n_layers=4, n_heads=4, n_kv_heads=2,
    d_ff=48, max_seq_len=64, dtype=jnp.float32,
)


def _tokens(b, s, seed=0):
    return jnp.asarray(np.random.default_rng(seed).integers(3, 60, size=(b, s)))


@pytest.mark.parametrize("n_stages,n_micro", [(2, 4), (4, 2), (4, 1), (2, 8)])
def test_pp_forward_matches_dense(n_stages, n_micro):
    params = init_params(jax.random.PRNGKey(0), CFG)
    toks = _tokens(8, 12)
    want = np.asarray(forward(params, CFG, toks))

    mesh = create_mesh(f"pp:{n_stages}")
    stacked = place_stacked(split_stages(params, CFG, n_stages), CFG, mesh)
    got = np.asarray(pp_forward(stacked, CFG, toks, mesh, n_micro=n_micro))
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


def test_split_stages_shapes_and_specs():
    params = init_params(jax.random.PRNGKey(1), CFG)
    stacked = split_stages(params, CFG, 2)
    assert stacked["stages"]["wq"].shape[:2] == (2, 2)  # [n_stages, per_stage]
    # stage 0 layer 1 == original layer 1
    np.testing.assert_array_equal(
        np.asarray(stacked["stages"]["wq"][0, 1]), np.asarray(params["layers"][1]["wq"])
    )
    specs = pp_param_specs(CFG)
    assert specs["stages"]["wq"] == P("pp")
    assert specs["embed"] == P()

    with pytest.raises(ValueError, match="stages"):
        split_stages(params, CFG, 3)  # 4 layers don't split into 3


def test_pp_forward_moe_layers():
    """MoE layers ride the same stage scan (router key survives stacking)."""
    cfg = LlamaConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=48, max_seq_len=64, dtype=jnp.float32,
        n_experts=4, n_experts_per_tok=2,
    )
    params = init_params(jax.random.PRNGKey(2), cfg)
    toks = _tokens(4, 9, seed=2)
    want = np.asarray(forward(params, cfg, toks))
    mesh = create_mesh("pp:2")
    stacked = place_stacked(split_stages(params, cfg, 2), cfg, mesh)
    got = np.asarray(pp_forward(stacked, cfg, toks, mesh, n_micro=2))
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


def test_pp_forward_gemma2_style_layers():
    """Sandwich post-norms, uniform sliding window, softcaps and query
    scale all ride the stage scan; alternating windows are rejected loudly
    (the scan applies one static mask)."""
    import dataclasses

    cfg = dataclasses.replace(
        CFG, post_norms=True, sliding_window=6, attn_softcap=5.0,
        final_softcap=10.0, query_scale=0.1,
    )
    params = init_params(jax.random.PRNGKey(6), cfg)
    toks = _tokens(4, 12, seed=6)
    want = np.asarray(forward(params, cfg, toks))
    mesh = create_mesh("pp:2")
    stacked = place_stacked(split_stages(params, cfg, 2), cfg, mesh)
    got = np.asarray(pp_forward(stacked, cfg, toks, mesh, n_micro=2))
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)

    alt = dataclasses.replace(cfg, alt_window=True)
    with pytest.raises(ValueError, match="alternating"):
        pp_forward(place_stacked(split_stages(params, alt, 2), alt, mesh), alt, toks, mesh)


@pytest.mark.skipif(
    tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 5),
    reason="pre-existing failure on old jax (<0.5): XLA donation shape "
    "check rejects the pp-sharded aliased input (Expected aliased input "
    "f32[2,2,32] vs f32[1,2,32]); passes on current jax",
)
def test_pp_train_step_reduces_loss():
    mesh = create_mesh("pp:2")
    step, init_state = make_pp_train_step(CFG, mesh, n_micro=2, lr=1e-2)
    stacked, opt_state = init_state(jax.random.PRNGKey(0))
    assert stacked["stages"]["wq"].sharding.spec == P("pp")
    toks = _tokens(4, 16, seed=3)
    losses = []
    for _ in range(8):
        stacked, opt_state, loss = step(stacked, opt_state, toks)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0] * 0.8, losses
