"""Profiling hooks: annotate/profile must be no-op-safe and capture traces."""

import numpy as np

from kakveda_tpu.core import profiling


def test_annotate_is_transparent():
    with profiling.annotate("unit.test"):
        x = np.arange(4).sum()
    assert x == 6


def test_profile_captures_trace(tmp_path):
    import jax.numpy as jnp

    logdir = tmp_path / "trace"
    with profiling.profile(logdir):
        with profiling.annotate("unit.matmul"):
            a = jnp.ones((8, 8))
            (a @ a).block_until_ready()
    produced = list(logdir.rglob("*"))
    assert produced, "profiler should write trace files"


def test_profile_survives_bad_logdir():
    with profiling.profile("/proc/definitely/not/writable"):
        pass  # must not raise
