"""Weight-only int8: reconstruction error bounds, logit closeness, and the
end-to-end quantized runtime."""

import jax
import jax.numpy as jnp
import numpy as np

from kakveda_tpu.models.generate import LlamaRuntime
from kakveda_tpu.models.llama import LlamaConfig, forward, init_params
from kakveda_tpu.models.quant import (
    quantization_error,
    quantize_params_int8,
    quantize_tensor_int8,
)

CFG = LlamaConfig(
    vocab_size=264, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq_len=128, dtype=jnp.float32,
)


def test_tensor_roundtrip_error_bound():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 32))
    q = quantize_tensor_int8(w)
    assert q["q"].dtype == jnp.int8 and q["s"].shape == (32,)
    recon = q["q"].astype(jnp.float32) * q["s"][None, :]
    # Symmetric per-column: error ≤ half a quantization step per column.
    err = jnp.max(jnp.abs(w - recon), axis=0)
    assert np.all(np.asarray(err) <= np.asarray(q["s"]) * 0.5 + 1e-7)


def test_quantized_logits_close_and_generation_runs():
    params = init_params(jax.random.PRNGKey(0), CFG)
    qparams = quantize_params_int8(params)
    assert quantization_error(params, qparams) < 0.01

    toks = jnp.asarray(np.random.default_rng(0).integers(3, 259, size=(2, 16)), jnp.int32)
    ref = np.asarray(forward(params, CFG, toks))
    got = np.asarray(forward(qparams, CFG, toks))
    # Logit agreement: high cosine similarity per position.
    a = ref.reshape(-1, CFG.vocab_size)
    b = got.reshape(-1, CFG.vocab_size)
    cos = (a * b).sum(-1) / (np.linalg.norm(a, axis=-1) * np.linalg.norm(b, axis=-1))
    assert cos.min() > 0.999, cos.min()

    rt = LlamaRuntime(cfg=CFG, seed=0, quant="int8")
    r = rt.generate("hello world", max_tokens=8)
    assert r.meta["provider"] == "tpu" and isinstance(r.text, str)
    # Deterministic under quantization too.
    assert rt.generate("hello world", max_tokens=8).text == r.text


def test_int8_quantizes_moe_expert_stacks():
    """Mixtral-style trees: stacked [E, in, out] expert weights quantize
    per-(expert, out-channel) — on MoE models they are ~95% of weight
    bytes, so skipping them would make quant=int8 a no-op."""
    cfg = LlamaConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=48, max_seq_len=64, dtype=jnp.float32,
        n_experts=4, n_experts_per_tok=2,
    )
    params = init_params(jax.random.PRNGKey(1), cfg)
    qparams = quantize_params_int8(params)
    qe = qparams["layers"][0]["we_gate"]
    assert qe["q"].dtype == jnp.int8 and qe["q"].shape == (4, 32, 48)
    assert qe["s"].shape == (4, 48)
    assert qparams["layers"][0]["router"].dtype != jnp.int8  # router kept f32
    assert quantization_error(params, qparams) < 0.01

    toks = jnp.asarray(np.random.default_rng(1).integers(3, 60, size=(2, 12)), jnp.int32)
    ref = np.asarray(forward(params, cfg, toks)).reshape(-1, cfg.vocab_size)
    got = np.asarray(forward(qparams, cfg, toks)).reshape(-1, cfg.vocab_size)
    cos = (ref * got).sum(-1) / (np.linalg.norm(ref, axis=-1) * np.linalg.norm(got, axis=-1))
    assert cos.min() > 0.995, cos.min()


def test_int8_tp_sharded_generation_matches_unsharded():
    """int8 + Megatron TP: the quantized tree shards (q like the weight,
    scale along the out axis) and greedy tokens match unsharded int8."""
    from jax.sharding import PartitionSpec as P

    from kakveda_tpu.models.generate import generate_tokens_fused
    from kakveda_tpu.models.hf_convert import shard_params
    from kakveda_tpu.models.llama import param_specs_like
    from kakveda_tpu.parallel.mesh import create_mesh

    params = init_params(jax.random.PRNGKey(0), CFG)
    qparams = quantize_params_int8(params)
    prompts = [[5, 6, 7], [10, 11, 12, 13]]
    single = generate_tokens_fused(qparams, CFG, prompts, max_new_tokens=8)

    mesh = create_mesh("dp:1,tp:2")
    specs = param_specs_like(qparams, CFG)
    assert specs["layers"][0]["wq"] == {"q": P(None, "tp"), "s": P("tp")}
    assert specs["layers"][0]["wo"] == {"q": P("tp", None), "s": P(None)}
    sq = shard_params(qparams, CFG, mesh)
    assert sq["layers"][0]["wq"]["q"].sharding.spec == P(None, "tp")
    tp_out = generate_tokens_fused(sq, CFG, prompts, max_new_tokens=8)
    assert tp_out == single
