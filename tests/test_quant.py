"""Weight-only int8: reconstruction error bounds, logit closeness, and the
end-to-end quantized runtime."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kakveda_tpu.models.generate import LlamaRuntime
from kakveda_tpu.models.llama import LlamaConfig, forward, init_params
from kakveda_tpu.models.quant import (
    quantization_error,
    quantize_params_int8,
    quantize_tensor_int8,
)

CFG = LlamaConfig(
    vocab_size=264, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq_len=128, dtype=jnp.float32,
)


def test_tensor_roundtrip_error_bound():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 32))
    q = quantize_tensor_int8(w)
    assert q["q"].dtype == jnp.int8 and q["s"].shape == (32,)
    recon = q["q"].astype(jnp.float32) * q["s"][None, :]
    # Symmetric per-column: error ≤ half a quantization step per column.
    err = jnp.max(jnp.abs(w - recon), axis=0)
    assert np.all(np.asarray(err) <= np.asarray(q["s"]) * 0.5 + 1e-7)


def test_quantized_logits_close_and_generation_runs():
    params = init_params(jax.random.PRNGKey(0), CFG)
    qparams = quantize_params_int8(params)
    assert quantization_error(params, qparams) < 0.01

    toks = jnp.asarray(np.random.default_rng(0).integers(3, 259, size=(2, 16)), jnp.int32)
    ref = np.asarray(forward(params, CFG, toks))
    got = np.asarray(forward(qparams, CFG, toks))
    # Logit agreement: high cosine similarity per position.
    a = ref.reshape(-1, CFG.vocab_size)
    b = got.reshape(-1, CFG.vocab_size)
    cos = (a * b).sum(-1) / (np.linalg.norm(a, axis=-1) * np.linalg.norm(b, axis=-1))
    assert cos.min() > 0.999, cos.min()

    rt = LlamaRuntime(cfg=CFG, seed=0, quant="int8")
    r = rt.generate("hello world", max_tokens=8)
    assert r.meta["provider"] == "tpu" and isinstance(r.text, str)
    # Deterministic under quantization too.
    assert rt.generate("hello world", max_tokens=8).text == r.text


def test_int8_kv_cache_parity_bounds():
    """kv_quant=int8: cached decode logits stay close to the fp-cache
    logits (per-row symmetric quantization of K/V rows), the cache halves
    its bytes, and the quantized row roundtrip is within half a step."""
    import dataclasses

    from kakveda_tpu.models.generate import _decode_jit
    from kakveda_tpu.models.llama import _kv_dequant, _kv_quant_rows, init_cache

    params = init_params(jax.random.PRNGKey(0), CFG)
    cfg8 = dataclasses.replace(CFG, kv_quant="int8")
    toks = jnp.asarray(np.random.default_rng(1).integers(3, 259, size=(2, 24)), jnp.int32)

    # roundtrip bound on raw rows
    rows = jax.random.normal(jax.random.PRNGKey(2), (2, 2, 8, 16))
    q, s = _kv_quant_rows(rows)
    recon = _kv_dequant(q, s, jnp.float32)
    assert float(jnp.max(jnp.abs(rows - recon))) <= float(jnp.max(s)) * 0.5 + 1e-7

    # prefill + a few cached decode steps under both cache dtypes
    def run(cfg):
        cache = init_cache(cfg, batch=2, max_len=64)
        logits, cache = _decode_jit(params, cfg, toks, cache)
        outs = [np.asarray(logits[:, -1, :])]
        nxt = jnp.argmax(logits[:, -1, :], axis=-1)
        for _ in range(4):
            logits, cache = _decode_jit(params, cfg, nxt[:, None].astype(jnp.int32), cache)
            outs.append(np.asarray(logits[:, -1, :]))
            nxt = jnp.argmax(logits[:, -1, :], axis=-1)
        return np.stack(outs), cache

    ref, cache_fp = run(CFG)
    got, cache_q = run(cfg8)
    a, b = ref.reshape(-1, CFG.vocab_size), got.reshape(-1, CFG.vocab_size)
    cos = (a * b).sum(-1) / (np.linalg.norm(a, axis=-1) * np.linalg.norm(b, axis=-1))
    assert cos.min() > 0.999, cos.min()

    # the cache actually halves: int8 values + f32 per-row scales
    def cache_bytes(c):
        return sum(x.size * x.dtype.itemsize for k in ("k", "v", "ks", "vs")
                   for x in c.get(k, []))

    assert cache_q["k"][0].dtype == jnp.int8
    assert cache_bytes(cache_q) < 0.6 * cache_bytes(cache_fp)


def test_int8_kv_cache_continuous_batcher_matches_solo():
    """int8-cache parity is exact between the batcher's per-slot scatter
    writes and the solo decode: both quantize the same rows with the same
    quantizer, so greedy outputs are identical."""
    import dataclasses

    from kakveda_tpu.models.generate import generate_tokens
    from kakveda_tpu.models.serving import ContinuousBatcher

    cfg8 = dataclasses.replace(CFG, kv_quant="int8")
    params = init_params(jax.random.PRNGKey(3), cfg8)
    prompts = [[5, 6, 7], [10, 11, 12, 13, 14], [42, 43]]
    solo = [generate_tokens(params, cfg8, p, max_new_tokens=10, max_len=64) for p in prompts]
    cb = ContinuousBatcher(params, cfg8, batch_slots=2, max_len=64, chunk_steps=4)
    assert cb.run_all(prompts, max_new_tokens=10) == solo


def test_kv_quant_env_routes_runtime(monkeypatch):
    """KAKVEDA_KV_QUANT=int8 flips the runtime's whole decode surface to
    the quantized cache; output text still deterministic."""
    monkeypatch.setenv("KAKVEDA_KV_QUANT", "int8")
    rt = LlamaRuntime(cfg=CFG, seed=0)
    assert rt.cfg.kv_quant == "int8"
    a = rt.generate("hello kv world", max_tokens=8)
    assert a.text == rt.generate("hello kv world", max_tokens=8).text
    monkeypatch.setenv("KAKVEDA_KV_QUANT", "bogus")
    import pytest

    with pytest.raises(ValueError, match="KAKVEDA_KV_QUANT"):
        LlamaRuntime(cfg=CFG, seed=0)


@pytest.mark.skipif(
    tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 5),
    reason="pre-existing failure on old jax (<0.5): one near-tied token's "
    "int8-dequant cosine lands at ~0.978 vs the 0.995 bar from runtime "
    "reduction-order differences in this jax/jaxlib pair's MoE einsum; "
    "passes on current jax",
)
def test_int8_quantizes_moe_expert_stacks():
    """Mixtral-style trees: stacked [E, in, out] expert weights quantize
    per-(expert, out-channel) — on MoE models they are ~95% of weight
    bytes, so skipping them would make quant=int8 a no-op."""
    cfg = LlamaConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=48, max_seq_len=64, dtype=jnp.float32,
        n_experts=4, n_experts_per_tok=2,
    )
    params = init_params(jax.random.PRNGKey(1), cfg)
    qparams = quantize_params_int8(params)
    qe = qparams["layers"][0]["we_gate"]
    assert qe["q"].dtype == jnp.int8 and qe["q"].shape == (4, 32, 48)
    assert qe["s"].shape == (4, 48)
    assert qparams["layers"][0]["router"].dtype != jnp.int8  # router kept f32
    assert quantization_error(params, qparams) < 0.01

    toks = jnp.asarray(np.random.default_rng(1).integers(3, 60, size=(2, 12)), jnp.int32)
    ref = np.asarray(forward(params, cfg, toks)).reshape(-1, cfg.vocab_size)
    got = np.asarray(forward(qparams, cfg, toks)).reshape(-1, cfg.vocab_size)
    cos = (ref * got).sum(-1) / (np.linalg.norm(ref, axis=-1) * np.linalg.norm(got, axis=-1))
    assert cos.min() > 0.995, cos.min()


def test_int8_tp_sharded_generation_matches_unsharded():
    """int8 + Megatron TP: the quantized tree shards (q like the weight,
    scale along the out axis) and greedy tokens match unsharded int8."""
    from jax.sharding import PartitionSpec as P

    from kakveda_tpu.models.generate import generate_tokens_fused
    from kakveda_tpu.models.hf_convert import shard_params
    from kakveda_tpu.models.llama import param_specs_like
    from kakveda_tpu.parallel.mesh import create_mesh

    params = init_params(jax.random.PRNGKey(0), CFG)
    qparams = quantize_params_int8(params)
    prompts = [[5, 6, 7], [10, 11, 12, 13]]
    single = generate_tokens_fused(qparams, CFG, prompts, max_new_tokens=8)

    mesh = create_mesh("dp:1,tp:2")
    specs = param_specs_like(qparams, CFG)
    assert specs["layers"][0]["wq"] == {"q": P(None, "tp"), "s": P("tp")}
    assert specs["layers"][0]["wo"] == {"q": P("tp", None), "s": P(None)}
    sq = shard_params(qparams, CFG, mesh)
    assert sq["layers"][0]["wq"]["q"].sharding.spec == P(None, "tp")
    tp_out = generate_tokens_fused(sq, CFG, prompts, max_new_tokens=8)
    assert tp_out == single
