"""Runtime concurrency sanitizer (kakveda_tpu/core/sanitize.py,
docs/robustness.md): named-lock edge/hold recording, cycle detection,
the asyncio loop-stall watchdog, and the chaos-marked cross-check that
merges the RUNTIME edge set observed under concurrent real-object
traffic with the STATIC lock-order graph and asserts the union stays
acyclic — the two halves of the concurrency pass agreeing on one graph.

No jax imports outside the chaos test's object construction.
"""

import asyncio
import threading
import time
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parents[1]

from kakveda_tpu.core import sanitize  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_state():
    sanitize.reset()
    yield
    sanitize.reset()


# ---------------------------------------------------------------------------
# SanitizedLock mechanics
# ---------------------------------------------------------------------------


def test_named_lock_plain_when_disarmed(monkeypatch):
    monkeypatch.delenv("KAKVEDA_SANITIZE", raising=False)
    lk = sanitize.named_lock("X._l")
    assert not isinstance(lk, sanitize.SanitizedLock)
    rl = sanitize.named_lock("X._r", kind="rlock")
    rl.acquire(); rl.acquire(); rl.release(); rl.release()  # an RLock


def test_edges_stats_and_reentrancy(monkeypatch):
    monkeypatch.setenv("KAKVEDA_SANITIZE", "1")
    a = sanitize.named_lock("A._x")
    b = sanitize.named_lock("B._y", kind="rlock")
    with a:
        with b:
            with b:  # reentrant: no self-edge, one hold
                pass
    rep = sanitize.sanitizer_report()
    assert rep["edges"] == [["A._x", "B._y", 1]]
    assert rep["cycles"] == []
    assert rep["locks"]["A._x"]["acquisitions"] == 1
    assert rep["locks"]["B._y"]["acquisitions"] == 1  # outermost only
    assert rep["locks"]["A._x"]["hold_ms_max"] >= 0.0


def test_contention_and_wait_accounting(monkeypatch):
    monkeypatch.setenv("KAKVEDA_SANITIZE", "1")
    lk = sanitize.named_lock("C._l")
    lk.acquire()
    t = threading.Thread(
        target=lambda: (lk.acquire(), lk.release()), daemon=True)
    t.start()
    time.sleep(0.05)
    lk.release()
    t.join(timeout=5.0)
    st = sanitize.sanitizer_report()["locks"]["C._l"]
    assert st["acquisitions"] == 2
    assert st["contended"] >= 1
    assert st["wait_ms_total"] >= 25.0


def test_condition_compatible(monkeypatch):
    monkeypatch.setenv("KAKVEDA_SANITIZE", "1")
    lk = sanitize.named_lock("D._l")
    cv = threading.Condition(lk)
    hits = []

    def waiter():
        with cv:
            cv.wait(timeout=5.0)
            hits.append(1)

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    time.sleep(0.05)
    with cv:
        cv.notify_all()
    t.join(timeout=5.0)
    assert hits == [1]
    assert not lk.locked()


def test_find_cycles():
    assert sanitize.find_cycles([("a", "b"), ("b", "c")]) == []
    cycles = sanitize.find_cycles([("a", "b"), ("b", "a"), ("b", "c")])
    assert cycles == [["a", "b", "a"]]


def test_inverted_order_reports_cycle(monkeypatch):
    monkeypatch.setenv("KAKVEDA_SANITIZE", "1")
    a = sanitize.named_lock("E._a")
    b = sanitize.named_lock("E._b")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    rep = sanitize.sanitizer_report()
    assert rep["cycles"] == [["E._a", "E._b", "E._a"]]


# ---------------------------------------------------------------------------
# loop-stall watchdog
# ---------------------------------------------------------------------------


def test_watchdog_detects_loop_stall():
    async def go():
        wd = sanitize.LoopStallWatchdog(threshold_ms=80)
        await wd.start()
        try:
            await asyncio.sleep(0.05)  # healthy heartbeat first
            time.sleep(0.4)            # THE sin: block the loop
            await asyncio.sleep(0.1)   # let the checker observe recovery
        finally:
            await wd.stop()
        return wd.stall_count

    stalls = asyncio.run(go())
    assert stalls >= 1
    rep = sanitize.sanitizer_report()
    assert rep["stalls"], "stall must be recorded in the report"
    evt = rep["stalls"][-1]
    assert evt["stall_ms"] >= 80
    # The captured stack is the loop thread's frames — the blocking
    # time.sleep call above must be visible in it.
    assert "time.sleep" in evt["stack"] or "go" in evt["stack"]


def test_watchdog_quiet_on_healthy_loop():
    async def go():
        wd = sanitize.LoopStallWatchdog(threshold_ms=200)
        await wd.start()
        try:
            for _ in range(10):
                await asyncio.sleep(0.01)
        finally:
            await wd.stop()
        return wd.stall_count

    assert asyncio.run(go()) == 0
    assert sanitize.sanitizer_report()["stalls"] == []


# ---------------------------------------------------------------------------
# chaos: runtime edges vs static graph, under real concurrent traffic
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_runtime_edges_consistent_with_static_graph(monkeypatch, tmp_path):
    """Arm KAKVEDA_SANITIZE=1, drive the real lock-owning objects (bus
    DLQ/breaker paths, admission + brownout ladder, fleet view, cluster
    state) concurrently from threads, then merge the OBSERVED edge set
    with the STATIC lock-order graph: the union must be acyclic, and no
    runtime edge may invert a static one. This is the cross-check the
    matching named_lock()/ClassName._attr node ids exist for."""
    monkeypatch.setenv("KAKVEDA_SANITIZE", "1")

    from kakveda_tpu.core.admission import (
        AdmissionController,
        BrownoutController,
    )
    from kakveda_tpu.events.bus import EventBus
    from kakveda_tpu.fleet.gossip import FleetView
    from kakveda_tpu.ops.incremental import ClusterState

    adm = AdmissionController(
        enabled=True,
        brownout=BrownoutController(enabled=True, enter=0.8, exit=0.5,
                                    dwell_s=0.0),
    )
    bus = EventBus(dlq_path=tmp_path / "dlq.jsonl")
    view = FleetView(ttl_s=1.0)
    cs = ClusterState(threshold=0.5, k=4)

    stop = threading.Event()
    errors = []
    seqs = iter(range(1, 1_000_000))

    def drive(fn):
        try:
            while not stop.is_set():
                fn()
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    def adm_path():
        try:
            with adm.slot("warn"):
                pass
        except Exception:  # noqa: BLE001 — sheds are the point of the storm
            pass
        adm.note_fleet_pressure(0.9, ttl_s=0.2)
        adm.brownout.occupancy()

    def bus_path():
        bus.breaker_states()
        bus.topics()

    def view_path():
        view.fold({"replica": "r1", "seq": next(seqs),
                   "ts": time.time(), "occupancy": 0.5})
        view.peers()
        view.fleet_pressure()

    def cs_path():
        cs.info()
        cs.labels()

    threads = [threading.Thread(target=drive, args=(f,), daemon=True)
               for f in (adm_path, bus_path, view_path, cs_path)]
    for t in threads:
        t.start()
    for i in range(20):
        cs.add_row(i, failure_type="t", failure_id=f"F-{i}", apps=("a",))
        cs.attach(i, [max(0, i - 1)], [0.9])
    time.sleep(0.5)
    stop.set()
    for t in threads:
        t.join(timeout=5.0)
    bus.close()
    assert not errors, errors

    runtime_edges = sanitize.lock_order_edges()
    assert sanitize.sanitizer_report()["cycles"] == []

    from kakveda_tpu.analysis.concurrency import static_lock_graph

    static_edges = static_lock_graph(ROOT)
    union = set(static_edges) | set(runtime_edges)
    assert sanitize.find_cycles(union) == [], (
        "runtime acquisition order contradicts the static lock-order "
        f"graph: {sorted(union)}"
    )
