"""HTTP service-layer tests against the reference REST contracts,
using aiohttp's in-process test server."""

import asyncio
import uuid
from datetime import datetime, timezone

import pytest
from aiohttp.test_utils import TestClient, TestServer

from kakveda_tpu.core.fingerprint import signature_text
from kakveda_tpu.models.runtime import STUB_RESPONSE
from kakveda_tpu.platform import Platform
from kakveda_tpu.service.app import make_agent_echo_app, make_app


def _trace(app_id, prompt, response=STUB_RESPONSE):
    return {
        "trace_id": str(uuid.uuid4()),
        "ts": datetime.now(timezone.utc).isoformat(),
        "app_id": app_id,
        "agent_id": "agent-1",
        "prompt": prompt,
        "response": response,
        "model": "stub",
        "temperature": 0.2,
        "tools": [],
        "env": {"os": "linux"},
    }


def run(coro):
    return asyncio.run(coro)


async def _with_client(app, fn):
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        return await fn(client)
    finally:
        await client.close()


@pytest.fixture()
def app(tmp_path):
    plat = Platform(data_dir=tmp_path / "data", capacity=256, dim=1024)
    return make_app(plat)


def test_healthz_readyz(app):
    async def go(client):
        r = await client.get("/healthz")
        assert r.status == 200 and (await r.json())["ok"]
        r = await client.get("/readyz")
        body = await r.json()
        assert body["ok"] and body["gfkb_count"] == 0

    run(_with_client(app, go))


def test_ingest_then_views(app):
    async def go(client):
        prompt = "Summarize this document and include citations even if not provided."
        r = await client.post("/ingest", json={"trace": _trace("app-A", prompt)})
        assert r.status == 200 and (await r.json())["ok"]
        await client.post("/ingest", json={"trace": _trace("app-B", "Explain paper and add references.")})

        r = await client.get("/failures")
        failures = (await r.json())["failures"]
        assert len(failures) == 2
        assert failures[0]["failure_id"] == "F-0001"

        r = await client.get("/patterns")
        patterns = (await r.json())["patterns"]
        assert len(patterns) == 1
        assert patterns[0]["affected_apps"] == ["app-A", "app-B"]

        r = await client.get("/health/app-A")
        pts = (await r.json())["points"]
        assert len(pts) == 1 and pts[0]["score"] == 85.0

    run(_with_client(app, go))


def test_warn_contract(app):
    async def go(client):
        prompt = "Summarize this document and include citations even if not provided."
        await client.post("/ingest", json={"trace": _trace("app-A", prompt)})
        r = await client.post(
            "/warn",
            json={"app_id": "app-C", "prompt": prompt, "tools": [], "env": {"os": "linux"}},
        )
        body = await r.json()
        assert r.status == 200
        assert body["action"] == "warn"
        assert body["confidence"] > 0.9
        assert body["references"][0]["failure_id"] == "F-0001"

    run(_with_client(app, go))


def test_warn_concurrent_batching(app):
    async def go(client):
        await client.post(
            "/ingest",
            json={"trace": _trace("app-A", "Summarize with citations please")},
        )
        reqs = [
            client.post(
                "/warn",
                json={"app_id": f"a{i}", "prompt": f"Summarize doc {i} with citations", "tools": [], "env": {}},
            )
            for i in range(32)
        ]
        responses = await asyncio.gather(*reqs)
        bodies = [await r.json() for r in responses]
        assert all(r.status == 200 for r in responses)
        assert all(b["action"] in ("warn", "block", "silent") for b in bodies)

    run(_with_client(app, go))


def test_match_and_upsert_endpoints(app):
    async def go(client):
        sig = signature_text("Summarize with citations", [], {"os": "linux"})
        r = await client.post(
            "/failures/upsert",
            json={
                "failure_type": "HALLUCINATION_CITATION",
                "signature_text": sig,
                "app_id": "x",
                "impact_severity": "medium",
                "resolution": "say no sources",
            },
        )
        body = await r.json()
        assert body["created"] and body["failure"]["failure_id"] == "F-0001"

        r = await client.post("/failures/match", json={"signature_text": sig})
        matches = (await r.json())["matches"]
        assert matches and matches[0]["score"] > 0.99

        r = await client.post(
            "/patterns/upsert",
            json={"name": "N", "failure_ids": ["F-0001"], "affected_apps": ["x", "y"]},
        )
        assert (await r.json())["pattern"]["pattern_id"] == "FP-0001"

    run(_with_client(app, go))


def test_validation_errors(app):
    async def go(client):
        r = await client.post("/ingest", json={"trace": {"bad": "shape"}})
        assert r.status == 422
        r = await client.post("/warn", json={"nope": 1})
        assert r.status == 422
        r = await client.post("/failures/upsert", json={"failure_type": "X"})
        assert r.status == 422
        r = await client.post("/subscribe", json={"topic": "t"})
        assert r.status == 422

    run(_with_client(app, go))


def test_pubsub_roundtrip(app, tmp_path):
    """External subscriber gets HTTP callbacks — the reference bus contract."""
    received = []
    echo = make_agent_echo_app()

    async def collector(request):
        received.append(await request.json())
        from aiohttp import web

        return web.json_response({"ok": True})

    echo.router.add_post("/collect", collector)

    async def go(client):
        echo_client = TestClient(TestServer(echo))
        await echo_client.start_server()
        try:
            cb = str(echo_client.make_url("/collect"))
            r = await client.post("/subscribe", json={"topic": "custom.topic", "callback_url": cb})
            assert (await r.json())["subscribers"] == 1

            r = await client.post("/publish", json={"topic": "custom.topic", "event": {"x": 1}})
            assert (await r.json())["delivered"] == 1
            assert received == [{"x": 1}]

            r = await client.get("/topics")
            assert (await r.json())["topics"]["custom.topic"] == 1
        finally:
            await echo_client.close()

    run(_with_client(app, go))


def test_agent_echo_contract():
    async def go(client):
        r = await client.get("/health")
        assert (await r.json())["status"] == "healthy"
        r = await client.get("/capabilities")
        assert "echo" in (await r.json())["capabilities"]
        r = await client.post("/invoke", json={"event_type": "ping", "payload": {"a": 1}})
        body = await r.json()
        assert body["status"] == "ok"
        assert body["events"][0]["payload"]["received_event_type"] == "ping"

    run(_with_client(make_agent_echo_app(), go))


def test_request_id_header(app):
    async def go(client):
        r = await client.get("/healthz", headers={"x-request-id": "rid-123"})
        assert r.headers["x-request-id"] == "rid-123"
        r = await client.get("/healthz")
        assert len(r.headers["x-request-id"]) == 32

    run(_with_client(app, go))


def test_patterns_mine_endpoint(tmp_path):
    """Device clustering over the GFKB via POST /patterns/mine."""
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from kakveda_tpu.core.schemas import Severity
    from kakveda_tpu.platform import Platform
    from kakveda_tpu.service.app import make_app

    async def go():
        plat = Platform(data_dir=tmp_path / "d", capacity=256, dim=1024)
        # two similar citation failures across apps + one unrelated
        for app_id in ("app-A", "app-B"):
            plat.gfkb.upsert_failure(
                failure_type="HALLUCINATION_CITATION",
                signature_text="intent:citations_required | summarize the quarterly report",
                app_id=app_id,
                impact_severity=Severity.medium,
            )
        plat.gfkb.upsert_failure(
            failure_type="TIMEOUT",
            signature_text="totally different failure shape xyz",
            app_id="app-C",
            impact_severity=Severity.low,
        )
        c = TestClient(TestServer(make_app(plat)))
        await c.start_server()
        try:
            r = await c.post("/patterns/mine", json={"threshold": 0.5})
            assert r.status == 200
            body = await r.json()
            assert body["ok"]
            names = [p["name"] for p in body["patterns"]]
            assert any("itation" in n for n in names), names
            # freshness fields: first call at a non-default threshold is a
            # full sweep that re-seeds the incremental baseline...
            assert body["mining"]["mode"] == "full"
            assert body["mining"]["wall_ms"] >= 0
            # ...so the second call is served from the streaming state.
            r = await c.post("/patterns/mine", json={"threshold": 0.5})
            assert (await r.json())["mining"]["mode"] == "incremental"
            r = await c.post(
                "/patterns/mine", json={"threshold": 0.5, "mode": "bogus"}
            )
            assert r.status == 422
        finally:
            await c.close()

    asyncio.run(go())


def test_dashboard_bus_subscriptions(tmp_path):
    """API-ingested traces land in the runs explorer; child-safety alerts
    become warning events (reference: dashboard/app.py:1332-1431)."""
    import asyncio
    from datetime import datetime, timezone

    from aiohttp.test_utils import TestClient, TestServer

    from kakveda_tpu.core.schemas import TracePayload
    from kakveda_tpu.dashboard.app import make_dashboard_app
    from kakveda_tpu.models.runtime import StubRuntime
    from kakveda_tpu.platform import Platform

    async def go():
        plat = Platform(data_dir=tmp_path / "d", capacity=256, dim=1024)
        app = make_dashboard_app(platform=plat, db_path=tmp_path / "dash.db", model=StubRuntime())
        c = TestClient(TestServer(app))
        await c.start_server()
        try:
            await plat.ingest(
                TracePayload(
                    trace_id="ev-1",
                    ts=datetime.now(timezone.utc),
                    app_id="bus-app",
                    agent_id="external",
                    prompt="hello",
                    response="world",
                    model=None,
                    tools=[],
                    env={},
                )
            )
            await plat.bus.publish(
                "child_safety_alert",
                {"app_id": "kids-app", "severity": "high", "message": "blocked topic"},
            )
            await c.post("/login", data={"email": "admin@local", "password": "admin123", "next": "/"})
            runs = await (await c.get("/runs?q=")).text()
            assert "ev-1" in runs
            warnings = await (await c.get("/warnings")).text()
            assert "kids-app" in warnings
        finally:
            await c.close()

    asyncio.run(go())


def test_ingest_batch_endpoint(tmp_path):
    """POST /ingest/batch: one validate + one device scatter per batch —
    the HTTP surface of the 10k traces/sec pipeline (the reference only
    has per-trace POSTs, services/ingestion/app.py:15-21). Failures found
    in the batch land in the GFKB and the count comes back."""

    async def go():
        plat = Platform(data_dir=tmp_path / "data", capacity=256, dim=1024)
        app = make_app(platform=plat)

        async def fn(client):
            traces = [
                _trace("app-b", f"Summarize doc {i} and include citations even if not provided.")
                for i in range(8)
            ]
            r = await client.post("/ingest/batch", json={"traces": traces})
            body = await r.json()
            assert r.status == 200, body
            assert body["ok"] is True and body["n"] == 8
            assert body["failures"] >= 1  # citation-bait prompts classify as failures
            assert plat.gfkb.count >= 1
            # empty batch: no-op, still ok
            r = await client.post("/ingest/batch", json={"traces": []})
            assert (await r.json()) == {"ok": True, "n": 0, "failures": 0}
            # malformed: 422, not a 500
            r = await client.post("/ingest/batch", json={"traces": [{"bad": 1}]})
            assert r.status == 422

        await _with_client(app, fn)

    run(go())
