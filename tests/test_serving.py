"""Continuous batching: greedy parity with solo generation, slot reuse,
admit-while-running."""

import jax
import numpy as np

from kakveda_tpu.models.generate import generate_tokens
from kakveda_tpu.models.llama import LlamaConfig, init_params
from kakveda_tpu.models.serving import ContinuousBatcher

CFG = LlamaConfig(
    vocab_size=264, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq_len=128, dtype=__import__("jax.numpy", fromlist=["x"]).float32,
)

def test_continuous_batcher_parity_and_reuse():
    params = init_params(jax.random.PRNGKey(0), CFG)
    prompts = [[5, 6, 7], [10, 11, 12, 13, 14], [42], [9, 8], [100, 101, 102, 103]]
    solo = [
        generate_tokens(params, CFG, p, max_new_tokens=10, max_len=64) for p in prompts
    ]

    # 2 slots for 5 requests → retirement + slot reuse + late admission.
    cb = ContinuousBatcher(params, CFG, batch_slots=2, max_len=64, chunk_steps=4)
    outs = cb.run_all(prompts, max_new_tokens=10)
    assert outs == solo


def test_continuous_batcher_admit_mid_flight():
    """A request admitted while another is mid-decode must not perturb it."""
    params = init_params(jax.random.PRNGKey(1), CFG)
    a, b = [5, 6, 7, 8], [50, 51]
    solo_a = generate_tokens(params, CFG, a, max_new_tokens=12, max_len=64)
    solo_b = generate_tokens(params, CFG, b, max_new_tokens=6, max_len=64)

    cb = ContinuousBatcher(params, CFG, batch_slots=3, max_len=64, chunk_steps=3)
    ra = cb.admit(a, max_new_tokens=12)
    cb.step()  # a decodes a chunk alone
    rb = cb.admit(b, max_new_tokens=6)  # b admitted mid-flight
    while cb.active:
        cb.step()
    assert cb.results[ra] == solo_a
    assert cb.results[rb] == solo_b


def test_batcher_and_warn_interleave_on_one_device():
    """Chip-sharing integration: decode chunks and pre-flight matches
    interleave on the same device without corrupting either — the batcher
    emits exact solo tokens while warn batches run between chunks."""
    import numpy as np

    from kakveda_tpu.ops.featurizer import HashedNGramFeaturizer
    from kakveda_tpu.ops.knn import ShardedKnn
    from kakveda_tpu.parallel.mesh import create_mesh

    params = init_params(jax.random.PRNGKey(0), CFG)
    prompts = [[5, 6, 7], [9, 8, 7, 6]]
    solo = [generate_tokens(params, CFG, p, max_new_tokens=12, max_len=64) for p in prompts]

    feat = HashedNGramFeaturizer(dim=256)
    knn = ShardedKnn(create_mesh("data:1"), capacity=64, dim=256, k=3)
    corpus = [f"intent_tags:a | prompt_hint:failure {i} | tools: | env_keys:os" for i in range(16)]
    emb, valid = knn.insert(*knn.alloc(), feat.encode_batch(corpus), np.arange(16, dtype=np.int32))

    cb = ContinuousBatcher(params, CFG, batch_slots=2, max_len=64, chunk_steps=4)
    rids = [cb.admit(p, max_new_tokens=12) for p in prompts]
    while cb.active:
        cb.step()
        # A warn batch between every chunk — shares the device queue.
        idx, val = feat.encode_batch_sparse(corpus[:5])
        scores, slots = knn.topk_result(knn.topk_async_sparse(emb, valid, idx, val))
        assert scores[0, 0] > 0.99 and slots[0, 0] == 0  # self-match intact
    assert [cb.results[r] for r in rids] == solo


def test_engine_levers_under_tp_sharding():
    """Continuous batching + prefix cache + speculative verify chunks all
    run with Megatron-TP-sharded params on a tp:2 mesh, token-identical to
    the single-device engine — XLA inserts the tp collectives from the
    param shardings inside every serving program (admit, prefix admit,
    chunk scan, verify chunk)."""
    from kakveda_tpu.models.hf_convert import shard_params
    from kakveda_tpu.parallel.mesh import create_mesh

    params = init_params(jax.random.PRNGKey(0), CFG)
    head = list(range(60, 76))
    prompts = [head + [5, 6, 7], head + [9], [42, 43]]

    def run(p):
        cb = ContinuousBatcher(p, CFG, batch_slots=2, max_len=64, chunk_steps=4, spec_k=4)
        assert cb.register_prefix(head)
        outs = cb.run_all(prompts, max_new_tokens=8)
        assert cb.prefix_stats["hits"] == 2 and cb.spec_stats["chunks"] > 0
        return outs

    single = run(params)
    mesh = create_mesh("dp:1,tp:2")
    assert run(shard_params(params, CFG, mesh)) == single


def test_per_request_temperature():
    """A sampled slot varies with the rng while a greedy slot in the SAME
    pool keeps exact parity with solo greedy decoding."""
    params = init_params(jax.random.PRNGKey(2), CFG)
    greedy_prompt, sampled_prompt = [5, 6, 7], [50, 51, 52]
    solo = generate_tokens(params, CFG, greedy_prompt, max_new_tokens=10, max_len=64)

    def run(seed):
        cb = ContinuousBatcher(
            params, CFG, batch_slots=2, max_len=64, chunk_steps=4,
            rng=jax.random.PRNGKey(seed),
        )
        rg = cb.admit(greedy_prompt, max_new_tokens=10)
        rs = cb.admit(sampled_prompt, max_new_tokens=10, temperature=1.5)
        while cb.active:
            cb.step()
        return cb.results[rg], cb.results[rs]

    g1, s1 = run(seed=1)
    g2, s2 = run(seed=2)
    assert g1 == solo and g2 == solo  # greedy slot unaffected by sampling
    assert s1 != s2  # sampled slot actually samples (different keys differ)


def test_chunked_prefill_matches_single_shot():
    """Chunked prefill (fixed-size pieces over the shared cache) must
    produce byte-identical greedy generations to single-shot prefill —
    including ragged prompt lengths that force extra left padding to
    reach the chunk multiple."""
    from kakveda_tpu.models.generate import DecodeSession

    params = init_params(jax.random.PRNGKey(3), CFG)
    prompts = [list(range(5, 32)), list(range(40, 49))]  # 27 and 9 tokens

    def run(prefill_chunk):
        sess = DecodeSession(
            params, CFG, prompts, chunk_steps=8, max_len=96, prefill_chunk=prefill_chunk
        )
        out = []
        while True:
            c = sess.step_chunk(8)
            if c is None or len(out) >= 2:
                break
            out.append(c)
        return np.concatenate(out, axis=1).tolist()

    single = run(0)
    for chunk in (8, 16):  # 27 rounds up to 32; both chunk sizes divide it
        assert run(chunk) == single, chunk


def test_serving_engine_concurrent_requests_one_pool():
    """The online engine: requests submitted concurrently decode in ONE
    shared slot pool (max_active > 1) and each comes back byte-identical
    to its solo greedy decode."""
    from kakveda_tpu.models.serving import ServingEngine

    params = init_params(jax.random.PRNGKey(0), CFG)
    prompts = [[5, 6, 7], [10, 11, 12, 13, 14], [42], [9, 8], [100, 101, 102, 103]]
    solo = [generate_tokens(params, CFG, p, max_new_tokens=10, max_len=64) for p in prompts]

    eng = ServingEngine(params, CFG, batch_slots=4, max_len=64, chunk_steps=4)
    try:
        futs = [eng.submit(p, max_new_tokens=10) for p in prompts]
        outs = [f.result(timeout=120) for f in futs]
        assert outs == solo
        assert eng.stats()["completed"] == len(prompts)
        assert eng.stats()["max_active"] >= 2  # actually shared, not serialized
        # per-request budgets: a late admit with its own max_tokens
        late = eng.generate_ids(prompts[0], max_new_tokens=3)
        assert late == solo[0][:3]
    finally:
        eng.close()


def test_serving_engine_rejects_oversized_and_recovers():
    """An admission that can't fit the slot window fails ONLY that future;
    the loop keeps serving everyone else."""
    from kakveda_tpu.models.serving import ServingEngine

    params = init_params(jax.random.PRNGKey(0), CFG)
    eng = ServingEngine(params, CFG, batch_slots=2, max_len=32, chunk_steps=4)
    try:
        assert not eng.fits(40, 4)  # prompt alone exceeds the window
        assert not eng.fits(10, 32)  # bucket(10)=16, 16+32+1 > 32
        assert eng.fits(10, 8)
        import pytest

        with pytest.raises(ValueError):
            eng.generate_ids(list(range(40)), max_new_tokens=4)
        ok = eng.generate_ids([5, 6, 7], max_new_tokens=8)
        assert ok == generate_tokens(params, CFG, [5, 6, 7], max_new_tokens=8, max_len=64)
    finally:
        eng.close()


def test_runtime_generate_routes_through_engine(monkeypatch):
    """LlamaRuntime.generate/generate_batch default to the shared engine
    (meta carries continuous=True) with output identical to the solo path;
    an oversized request transparently falls back to the per-call decode."""
    from concurrent.futures import ThreadPoolExecutor

    from kakveda_tpu.models.generate import LlamaRuntime

    cfg = LlamaConfig(
        vocab_size=264, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=48, max_seq_len=256, dtype=jax.numpy.float32,
    )
    monkeypatch.delenv("KAKVEDA_PREFILL_CHUNK", raising=False)
    monkeypatch.setenv("KAKVEDA_SERVE_CONTINUOUS", "0")
    rt_off = LlamaRuntime(cfg=cfg, seed=0)
    prompts = ["alpha failure", "beta timeout in retrieval", "gamma"]
    off = [rt_off.generate(p, max_tokens=10) for p in prompts]
    assert all("continuous" not in r.meta for r in off)

    monkeypatch.delenv("KAKVEDA_SERVE_CONTINUOUS", raising=False)
    rt = LlamaRuntime(cfg=cfg, seed=0)
    with ThreadPoolExecutor(3) as ex:
        on = list(ex.map(lambda p: rt.generate(p, max_tokens=10), prompts))
    assert [r.text for r in on] == [r.text for r in off]
    assert all(r.meta.get("continuous") for r in on)
    assert rt._engine is not None and rt._engine.stats()["completed"] == 3

    # batch entry joins the same shared pool
    batch = rt.generate_batch(prompts, max_tokens=10)
    assert [r.text for r in batch] == [r.text for r in off]
    assert all(r.meta.get("continuous") for r in batch)
    assert rt._engine.stats()["completed"] == 6

    # oversized budget → solo fallback, same engine still alive
    monkeypatch.setenv("KAKVEDA_SERVE_WINDOW", "32")
    rt2 = LlamaRuntime(cfg=cfg, seed=0)
    big = rt2.generate("x " * 20, max_tokens=64)
    assert "continuous" not in big.meta
    rt._engine.close()
    if rt2._engine is not None:
        rt2._engine.close()


def test_serving_engine_loop_death_fails_futures_not_hangs(monkeypatch):
    """If the decode loop dies (device error mid-chunk) with the restart
    budget exhausted, pending futures must FAIL — callers blocked on
    result() would otherwise hang forever — and later submits must raise
    EngineDeadError IMMEDIATELY instead of enqueueing into a queue nobody
    drains. The runtime layer then falls back to the solo decode path.
    (Restart/recovery semantics under a non-zero budget: tests/test_chaos.py.)"""
    import pytest

    from kakveda_tpu.models.serving import EngineDeadError, ServingEngine

    monkeypatch.setenv("KAKVEDA_SERVE_RESTARTS", "0")
    params = init_params(jax.random.PRNGKey(0), CFG)
    eng = ServingEngine(params, CFG, batch_slots=2, max_len=64, chunk_steps=4)

    def boom():
        raise RuntimeError("synthetic device error")

    eng.cb.step_async = boom  # next chunk dispatch kills the loop
    fut = eng.submit([5, 6, 7], max_new_tokens=8)
    with pytest.raises(EngineDeadError, match="died terminally"):
        fut.result(timeout=30)
    import time as _t

    for _ in range(50):  # loop marks itself dead promptly
        if eng._dead.is_set():
            break
        _t.sleep(0.1)
    with pytest.raises(EngineDeadError):
        eng.submit([5], max_new_tokens=2)
    with pytest.raises(EngineDeadError):
        eng.register_prefix(list(range(16)))


def test_runtime_masks_padded_vocab_for_byte_tokenizer():
    """The default config pads the vocab table past the ByteTokenizer's
    259 decodable ids; the runtime must clamp effective_vocab so no decode
    path can argmax an undecodable id (observed as stochastic playground
    500s: ByteTokenizer.decode raising 'bytes must be in range')."""
    from kakveda_tpu.models.generate import LlamaRuntime

    rt = LlamaRuntime(seed=0)
    assert rt.cfg.vocab_size == 264
    assert rt.cfg.effective_vocab == rt.tokenizer.vocab_size == 259
    rt.generate("any prompt at all", max_tokens=8)  # must not raise on decode


def test_chunked_prefill_env_serving_path(monkeypatch):
    """KAKVEDA_PREFILL_CHUNK routes LlamaRuntime serving through chunked
    prefill with identical output; a prompt that fits one chunk skips the
    rounding entirely (no widened window)."""
    from kakveda_tpu.models.generate import LlamaRuntime, _prefill_width

    cfg = LlamaConfig(
        vocab_size=264, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=48, max_seq_len=256, dtype=jax.numpy.float32,
    )
    rt = LlamaRuntime(cfg=cfg, seed=0)
    monkeypatch.setenv("KAKVEDA_SERVE_CONTINUOUS", "0")  # exercise the chunked path itself
    monkeypatch.delenv("KAKVEDA_PREFILL_CHUNK", raising=False)
    plain = rt.generate("hello failure world, summarize with citations", max_tokens=12)
    monkeypatch.setenv("KAKVEDA_PREFILL_CHUNK", "8")
    chunked = rt.generate("hello failure world, summarize with citations", max_tokens=12)
    assert chunked.text == plain.text

    # short prompts never round (a chunk >= the prompt would only pad)
    assert _prefill_width(10, 512) == 10
    assert _prefill_width(513, 512) == 1024
    assert _prefill_width(27, 8) == 32


def test_engine_chunk_pipelining_parity(monkeypatch):
    """Pipelined chunk dispatch (dispatch i+1 before fetching i's tokens —
    the remote-RTT overlap lever) must be token-identical to the
    unpipelined engine AND to solo decodes, across retirement lag, slot
    reuse, varied lengths, and EOS mid-chunk."""
    from kakveda_tpu.models.serving import ServingEngine

    params = init_params(jax.random.PRNGKey(2), CFG)
    prompts = [[5, 6, 7], [10, 11, 12, 13, 14], [42], [9, 8], [100, 101], [7, 7, 7]]
    budgets = [3, 10, 7, 1, 12, 5]  # mixed lengths force staggered retirement
    solo = [
        generate_tokens(params, CFG, p, max_new_tokens=m, max_len=64)
        for p, m in zip(prompts, budgets)
    ]

    def run(pipeline: str):
        monkeypatch.setenv("KAKVEDA_SERVE_PIPELINE", pipeline)
        # 2 slots for 6 requests: constant churn, so retirement lag and
        # admission delay are both exercised.
        eng = ServingEngine(params, CFG, batch_slots=2, max_len=64, chunk_steps=4)
        try:
            futs = [
                eng.submit(p, max_new_tokens=m) for p, m in zip(prompts, budgets)
            ]
            return [f.result(timeout=120) for f in futs]
        finally:
            eng.close()

    assert run("0") == solo
    assert run("1") == solo


def test_engine_pipelining_with_eos(monkeypatch):
    """EOS stopping under pipelining: the overshoot chunk's post-EOS tokens
    must be discarded, matching the unpipelined engine exactly."""
    from kakveda_tpu.models.serving import ServingEngine

    params = init_params(jax.random.PRNGKey(3), CFG)
    prompts = [[5, 6, 7, 8], [50, 51], [42, 43, 44]]
    # Pick each prompt's own 3rd greedy token as its EOS so stopping
    # happens mid-stream at different steps per slot.
    solo_full = [generate_tokens(params, CFG, p, max_new_tokens=12, max_len=64) for p in prompts]

    def run(pipeline: str, eos_id):
        monkeypatch.setenv("KAKVEDA_SERVE_PIPELINE", pipeline)
        eng = ServingEngine(
            params, CFG, batch_slots=3, max_len=64, chunk_steps=4, eos_id=eos_id
        )
        try:
            futs = [eng.submit(p, max_new_tokens=12) for p in prompts]
            return [f.result(timeout=120) for f in futs]
        finally:
            eng.close()

    eos = solo_full[0][2]  # slot 0 stops at step 3; others wherever it appears
    assert run("1", eos) == run("0", eos)
