"""Serving load test with SLOs (VERDICT r4 #6): many concurrent HTTP
clients drive playground generation through the real aiohttp server while
a pre-flight warn stream runs against the service API — asserting
(a) solo-greedy parity of every generated output under contention,
(b) p50/p95 request-latency SLOs, and (c) the warn stream's p95 while the
decode load runs. The reference serves playground/eval strictly
sequentially (services/dashboard/app.py:3127-3299, 2315-2393); this is
the capability it cannot exercise.

In-process ServingEngine invariants are covered by tests/test_serving.py;
this file covers the HTTP→engine path under real socket concurrency
(aiohttp TestServer binds a real port; requests traverse the full
middleware/auth/CSRF stack).
"""

import asyncio
import time

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from kakveda_tpu.dashboard.app import make_dashboard_app
from kakveda_tpu.platform import Platform
from kakveda_tpu.service.app import make_app as make_service_app

# Generous CPU-mesh SLOs: the tiny model decodes in tens of ms; the bound
# exists to catch serialization collapse (e.g. engine lock held across a
# whole generation → latency stacks linearly with concurrency), not to
# measure the hardware. TPU SLOs are bench.py's serve metric.
PLAYGROUND_P95_S = 30.0
WARN_P95_S = 5.0
N_CLIENTS = 12
REQS_PER_CLIENT = 2


@pytest.fixture()
def tiny_runtime(monkeypatch):
    import jax.numpy as jnp

    from kakveda_tpu.models.generate import LlamaRuntime
    from kakveda_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig(
        vocab_size=264, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=48, max_seq_len=256, dtype=jnp.float32,
    )
    # Solo (engine-off) greedy outputs are the parity oracle.
    monkeypatch.setenv("KAKVEDA_SERVE_CONTINUOUS", "0")
    solo_rt = LlamaRuntime(cfg=cfg, seed=0)
    monkeypatch.delenv("KAKVEDA_SERVE_CONTINUOUS", raising=False)
    rt = LlamaRuntime(cfg=cfg, seed=0)
    yield rt, solo_rt
    if rt._engine is not None:
        rt._engine.close()


def test_concurrent_playground_load_with_warn_stream(tmp_path, tiny_runtime):
    rt, solo_rt = tiny_runtime
    prompts = [f"failure report number {i} about timeouts" for i in range(N_CLIENTS)]
    solo = {p: solo_rt.generate(p, max_tokens=8).text for p in prompts}

    plat = Platform(data_dir=tmp_path / "data", capacity=512, dim=1024)
    dash = make_dashboard_app(platform=plat, db_path=tmp_path / "dash.db", model=rt)
    svc = make_service_app(platform=plat)

    lat_play: list = []
    lat_warn: list = []
    stop = asyncio.Event()

    async def login(client):
        r = await client.post(
            "/login",
            data={"email": "admin@local", "password": "admin123", "next": "/"},
            allow_redirects=False,
        )
        assert r.status == 302

    async def play_worker(client, prompt):
        for _ in range(REQS_PER_CLIENT):
            t0 = time.perf_counter()
            r = await client.post(
                "/playground/run", data={"prompt": prompt, "target": "model"}
            )
            body = await r.text()
            lat_play.append(time.perf_counter() - t0)
            assert r.status == 200, body[:300]
            assert solo[prompt] in body, (
                f"output for {prompt!r} under load != solo greedy decode"
            )

    async def warn_worker(svc_client):
        i = 0
        while not stop.is_set():
            t0 = time.perf_counter()
            r = await svc_client.post(
                "/warn",
                json={
                    "app_id": "load-app",
                    "prompt": f"Summarize doc {i} and include citations even if not provided.",
                },
            )
            await r.json()
            lat_warn.append(time.perf_counter() - t0)
            assert r.status == 200
            i += 1
            await asyncio.sleep(0.01)

    async def go():
        # Distinct TestClients = distinct sockets + cookie jars: each of the
        # N_CLIENTS "users" logs in separately, like a real load test.
        server = TestServer(dash)
        await server.start_server()
        svc_server = TestServer(svc)
        await svc_server.start_server()
        clients = [TestClient(server) for _ in range(N_CLIENTS)]
        svc_client = TestClient(svc_server)
        try:
            for c in clients:
                await c.start_server()
                await login(c)
            await svc_client.start_server()
            # Warm the μ-batch warn path once so its compile isn't inside SLO.
            await (await svc_client.post(
                "/warn", json={"app_id": "warm", "prompt": "warm up please"}
            )).json()
            warn_task = asyncio.create_task(warn_worker(svc_client))
            await asyncio.gather(
                *(play_worker(c, p) for c, p in zip(clients, prompts))
            )
            stop.set()
            await warn_task
        finally:
            for c in clients:
                await c.close()
            await svc_client.close()

    asyncio.run(go())

    assert len(lat_play) == N_CLIENTS * REQS_PER_CLIENT
    p50p, p95p = np.percentile(lat_play, [50, 95])
    p95w = float(np.percentile(lat_warn, 95)) if lat_warn else 0.0
    print(
        f"\nload: playground p50={p50p*1000:.0f}ms p95={p95p*1000:.0f}ms "
        f"({len(lat_play)} reqs, {N_CLIENTS} clients) — "
        f"warn p95={p95w*1000:.1f}ms ({len(lat_warn)} reqs)"
    )
    assert p95p < PLAYGROUND_P95_S, f"playground p95 {p95p:.1f}s over SLO"
    if lat_warn:
        assert p95w < WARN_P95_S, f"warn p95 {p95w:.1f}s over SLO"
    # All generations went through ONE shared engine (continuous batching),
    # not per-request pools.
    assert rt._engine is not None
    assert rt._engine.stats()["completed"] >= N_CLIENTS * REQS_PER_CLIENT
