"""Prefix caching: requests sharing a registered prompt prefix prefill
only their suffix, with greedy outputs token-identical to the uncached
path (the engine's parity invariant extends to prefix admissions).

Capability context: the reference resends the full prompt to Ollama on
every request (services/dashboard/app.py:1182-1258) — the shared head of
a judge template or system preamble is recomputed per call. Here its K/V
is computed once per process and scattered into each admitted slot.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kakveda_tpu.models.generate import generate_tokens
from kakveda_tpu.models.llama import LlamaConfig, init_params
from kakveda_tpu.models.serving import ContinuousBatcher, ServingEngine

CFG = LlamaConfig(
    vocab_size=264, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq_len=128, dtype=jnp.float32,
)

PREFIX = list(range(40, 56))  # 16 shared tokens


def _prompts():
    return [
        PREFIX + [5, 6, 7],
        PREFIX + list(range(100, 121)),  # long suffix → wider suffix chunk
        PREFIX + [9],
        list(PREFIX),  # prompt == prefix exactly (tail recompute path)
        [7, 8, 9],  # no shared prefix → normal admission
    ]


def test_prefix_admission_parity():
    params = init_params(jax.random.PRNGKey(0), CFG)
    prompts = _prompts()
    solo = [
        generate_tokens(params, CFG, p, max_new_tokens=10, max_len=128) for p in prompts
    ]

    cb = ContinuousBatcher(params, CFG, batch_slots=2, max_len=128, chunk_steps=4)
    assert cb.register_prefix(PREFIX)
    outs = cb.run_all(prompts, max_new_tokens=10)
    assert outs == solo
    # 4 of 5 prompts start with the prefix; all matched admissions save
    # at least one slab token.
    assert cb.prefix_stats["registered"] == 1
    assert cb.prefix_stats["hits"] == 4
    assert cb.prefix_stats["hit_tokens_saved"] > 0


def test_prefix_admission_parity_int8_kv():
    cfg = LlamaConfig(
        vocab_size=264, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, dtype=jnp.float32, kv_quant="int8",
    )
    params = init_params(jax.random.PRNGKey(1), cfg)
    prompts = _prompts()[:3]
    solo = [
        generate_tokens(params, cfg, p, max_new_tokens=8, max_len=128) for p in prompts
    ]
    cb = ContinuousBatcher(params, cfg, batch_slots=2, max_len=128, chunk_steps=4)
    assert cb.register_prefix(PREFIX)
    assert cb.run_all(prompts, max_new_tokens=8) == solo


def test_prefix_admission_parity_sliding_window():
    """Mistral-style sliding window: the suffix prefill's banded attention
    over slab rows must match the single-shot prefill exactly (same
    decode_step path as chunked prefill, but worth locking — the band
    crosses the slab/suffix boundary)."""
    cfg = LlamaConfig(
        vocab_size=264, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, dtype=jnp.float32, sliding_window=12,
    )
    params = init_params(jax.random.PRNGKey(4), cfg)
    prompts = _prompts()[:3]
    solo = [
        generate_tokens(params, cfg, p, max_new_tokens=8, max_len=128) for p in prompts
    ]
    cb = ContinuousBatcher(params, cfg, batch_slots=2, max_len=128, chunk_steps=4)
    assert cb.register_prefix(PREFIX)
    assert cb.run_all(prompts, max_new_tokens=8) == solo
    assert cb.prefix_stats["hits"] >= 2


def test_prefix_matching_rules():
    params = init_params(jax.random.PRNGKey(2), CFG)
    cb = ContinuousBatcher(params, CFG, batch_slots=2, max_len=64, chunk_steps=4)
    # Too short to matter / too long for the slot window: refused.
    assert not cb.register_prefix([1, 2, 3])
    assert not cb.register_prefix(list(range(60)))
    # Registered twice: idempotent.
    assert cb.register_prefix(PREFIX)
    assert cb.register_prefix(PREFIX)
    assert cb.prefix_stats["registered"] == 1
    # Non-matching prompt: no hit.
    assert cb._match_prefix([1, 2, 3, 4]) is None
    # Longest registered prefix wins.
    longer = PREFIX + [77, 78, 79, 80]
    assert cb.register_prefix(longer)
    m = cb._match_prefix(longer + [5])
    assert m is not None and list(m[0].ids) == longer


def test_prefix_refused_for_longrope():
    """Phi-3 longrope selects the RoPE regime from the FULL sequence
    length — a prefix computed at its own length could rotate in the
    wrong regime, so registration refuses (correctness over reuse)."""
    cfg = LlamaConfig(
        vocab_size=264, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, dtype=jnp.float32,
        rope_dim_factors=tuple([1.0] * 8), rope_dim_factors_long=tuple([2.0] * 8),
        rope_original_max_len=32,
    )
    params = init_params(jax.random.PRNGKey(3), cfg)
    cb = ContinuousBatcher(params, cfg, batch_slots=2, max_len=64, chunk_steps=4)
    assert not cb.register_prefix(PREFIX)


def test_engine_register_prefix_concurrent():
    """Engine-level registration runs on the loop thread and concurrent
    submits keep exact solo parity with the prefix cache active."""
    from concurrent.futures import ThreadPoolExecutor

    params = init_params(jax.random.PRNGKey(0), CFG)
    prompts = _prompts()
    solo = [
        generate_tokens(params, CFG, p, max_new_tokens=10, max_len=128) for p in prompts
    ]
    eng = ServingEngine(params, CFG, batch_slots=2, max_len=128, chunk_steps=4)
    try:
        assert eng.register_prefix(PREFIX)
        with ThreadPoolExecutor(max_workers=len(prompts)) as ex:
            outs = list(ex.map(lambda p: eng.generate_ids(p, 10), prompts))
        assert outs == solo
        assert eng.cb.prefix_stats["hits"] == 4
    finally:
        eng.close()


def test_prefix_lru_bound(monkeypatch):
    """The slab store is bounded: registrations past the cap evict the
    least recently USED prefix (hits refresh recency), so auto-registered
    eval heads can't grow HBM residency without limit."""
    monkeypatch.setenv("KAKVEDA_SERVE_PREFIX_MAX", "2")
    params = init_params(jax.random.PRNGKey(0), CFG)
    cb = ContinuousBatcher(params, CFG, batch_slots=2, max_len=128, chunk_steps=4)
    p1, p2, p3 = (
        [10] * 12,
        [20] * 12,
        [30] * 12,
    )
    assert cb.register_prefix(p1)
    assert cb.register_prefix(p2)
    # Touch p1 so p2 becomes the LRU victim.
    assert cb._match_prefix(p1 + [1, 2]) is not None
    assert cb.register_prefix(p3)
    assert tuple(p1) in cb._prefixes and tuple(p3) in cb._prefixes
    assert tuple(p2) not in cb._prefixes


def test_generate_batch_auto_registers_common_head(monkeypatch):
    """LlamaRuntime.generate_batch registers the batch's common token
    prefix so eval/judge batches reuse their instruction template's K/V
    without any explicit call."""
    from kakveda_tpu.models.generate import LlamaRuntime

    monkeypatch.setenv("KAKVEDA_SERVE_CONTINUOUS", "1")
    rt = LlamaRuntime(cfg=CFG, seed=0)
    try:
        # Short prompts: the runtime keeps only the last max_seq_len//2
        # tokens, and truncation would misalign the shared head.
        head = "Shared judge instruction template: "
        prompts = [head + t for t in ("a", "b", "c")]
        solo = [rt_out.text for rt_out in (rt.generate(p, max_tokens=6) for p in prompts)]
        outs = rt.generate_batch(prompts, max_tokens=6)
        assert [o.text for o in outs] == solo
        eng = rt._engine
        assert eng is not None
        assert eng.cb.prefix_stats["registered"] >= 1
        assert eng.cb.prefix_stats["hits"] >= 2
    finally:
        rt.retire()


def test_admin_prefix_registration(tmp_path, monkeypatch):
    """The ops panel registers a prefix on the live engine and the stats
    row reflects it; runtimes without support get 'unsupported'."""
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from kakveda_tpu.dashboard.app import make_dashboard_app
    from kakveda_tpu.dashboard.core import RATE_LIMITER
    from kakveda_tpu.models.generate import LlamaRuntime
    from kakveda_tpu.platform import Platform

    monkeypatch.setenv("KAKVEDA_SERVE_CONTINUOUS", "1")
    RATE_LIMITER._hits.clear()
    rt = LlamaRuntime(cfg=CFG, seed=0)
    plat = Platform(data_dir=tmp_path / "data", capacity=256, dim=1024)
    app = make_dashboard_app(platform=plat, db_path=tmp_path / "dash.db", model=rt)

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post(
                "/login",
                data={"email": "admin@local", "password": "admin123", "next": "/"},
                allow_redirects=False,
            )
            assert r.status == 302
            body = await (await client.get("/admin/serving")).text()
            assert "Register a serving prefix" in body
            r = await client.post(
                "/admin/serving/prefix",
                data={"prefix": "The shared system preamble for all requests. "},
                allow_redirects=False,
            )
            assert r.status == 302 and "registered" in r.headers["Location"]
            # The engine exists now and holds the prefix.
            assert rt._engine is not None
            assert rt._engine.cb.prefix_stats["registered"] == 1
        finally:
            await client.close()

    asyncio.run(go())
    rt.retire()


def test_prefix_disabled_by_env(monkeypatch):
    monkeypatch.setenv("KAKVEDA_SERVE_PREFIX", "0")
    params = init_params(jax.random.PRNGKey(0), CFG)
    cb = ContinuousBatcher(params, CFG, batch_slots=2, max_len=128, chunk_steps=4)
    assert cb.register_prefix(PREFIX)
    cb.run_all([PREFIX + [5, 6, 7]], max_new_tokens=4)
    assert cb.prefix_stats["hits"] == 0
