"""Speculative decoding inside the continuous-batching engine: each chunk
verifies k host-drafted tokens in ONE forward, advancing greedy slots
1..k+1 tokens per weight stream — with outputs TOKEN-IDENTICAL to the
plain chunked path (accepted drafts equal their own greedy verdicts by
construction; corrections are greedy).

Decode is weight-bandwidth-bound, so the k+1-wide verify rides the same
weight stream as a 1-wide step; on repetitive traffic (judge templates,
citation lists) acceptance multiplies tokens/stream. KAKVEDA_SERVE_SPEC=k
enables it on the engine; sampled slots fall back to plain chunks.
"""

import jax
import jax.numpy as jnp
import numpy as np

from kakveda_tpu.models.generate import generate_tokens
from kakveda_tpu.models.llama import LlamaConfig, init_params
from kakveda_tpu.models.serving import ContinuousBatcher, ServingEngine

CFG = LlamaConfig(
    vocab_size=264, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq_len=128, dtype=jnp.float32,
)

PROMPTS = [[5, 6, 7], [10, 11, 12, 13, 14], [42], [9, 8]]


def _solo(params, cfg, n=12):
    return [
        generate_tokens(params, cfg, p, max_new_tokens=n, max_len=128) for p in PROMPTS
    ]


def test_spec_chunk_parity_multi_slot():
    """run_all's step() dispatches to verify chunks for a greedy pool."""
    params = init_params(jax.random.PRNGKey(0), CFG)
    solo = _solo(params, CFG)
    cb = ContinuousBatcher(params, CFG, batch_slots=2, max_len=128, chunk_steps=4, spec_k=4)
    assert cb.run_all(PROMPTS, max_new_tokens=12) == solo
    assert cb.spec_stats["chunks"] > 0
    # Every chunk emits at least one token per active slot.
    assert cb.spec_stats["emitted"] >= cb.spec_stats["slot_chunks"]


def test_draft_lookup_semantics():
    """The host draft heuristic itself (acceptance-neutral to parity, so
    only a direct test catches a shift bug that would silently collapse
    the speculative win): longest recent suffix match, copy SHIFTED by
    one (the copy's first token is the t0 analog, not a draft)."""
    d = ContinuousBatcher._draft
    # History "A B C x ... A B C" — 3-token suffix matches at j=2; the
    # t0 analog is hist[3] (=9), drafts start at hist[4].
    hist = [7, 8, 3, 9, 4, 5, 7, 8, 3]
    assert d(hist, 4) == [4, 5, 7, 8]
    # Single-token match only: last token 3 occurred at j=1; t0 analog is
    # hist[2], drafts from hist[3].
    hist2 = [1, 3, 6, 2, 5, 3]
    assert d(hist2, 3) == [2, 5, 3]
    # Longest match preferred over a more recent shorter one: suffix
    # [8, 3] matches ending at j=2 even though a later lone 3 sits at
    # j=4; the t0 analog is hist[3] (=1), drafts start at hist[4].
    hist3 = [9, 8, 3, 1, 3, 2, 8, 3]
    assert d(hist3, 2) == [3, 2]
    # No earlier occurrence / degenerate history: PAD drafts.
    assert d([1, 2, 3], 3) == [0, 0, 0]
    assert d([5], 2) == [0, 0]
    assert d([], 2) == [0, 0]
    # Tail shorter than k pads with PAD.
    hist4 = [4, 6, 4]
    assert d(hist4, 4) == [4, 0, 0, 0]


def test_spec_acceptance_on_repetitive_traffic():
    """A prompt that forces token repetition must accept drafts: emitted
    tokens per slot-chunk > 1 on average (the spec win exists)."""
    params = init_params(jax.random.PRNGKey(1), CFG)
    # Random-init models tend to settle into repeating argmax loops, and
    # a repeated prompt primes the bigram lookup.
    p = [7, 8, 9, 7, 8, 9, 7, 8, 9]
    solo = generate_tokens(params, CFG, p, max_new_tokens=24, max_len=128)
    cb = ContinuousBatcher(params, CFG, batch_slots=1, max_len=128, chunk_steps=4, spec_k=4)
    rid = cb.admit(p, max_new_tokens=24)
    while cb.slots:
        cb.step_spec()
    assert cb.results[rid] == solo
    rate = cb.spec_stats["emitted"] / cb.spec_stats["slot_chunks"]
    assert rate > 1.0, cb.spec_stats


def test_spec_parity_int8_kv():
    cfg = LlamaConfig(
        vocab_size=264, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, dtype=jnp.float32, kv_quant="int8",
    )
    params = init_params(jax.random.PRNGKey(2), cfg)
    solo = _solo(params, cfg, n=8)
    cb = ContinuousBatcher(params, cfg, batch_slots=2, max_len=128, chunk_steps=4, spec_k=4)
    assert cb.run_all(PROMPTS, max_new_tokens=8) == solo


def test_spec_parity_sliding_window():
    cfg = LlamaConfig(
        vocab_size=264, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, dtype=jnp.float32, sliding_window=12,
    )
    params = init_params(jax.random.PRNGKey(3), cfg)
    solo = _solo(params, cfg, n=10)
    cb = ContinuousBatcher(params, cfg, batch_slots=2, max_len=128, chunk_steps=4, spec_k=4)
    assert cb.run_all(PROMPTS, max_new_tokens=10) == solo


def test_engine_spec_greedy_and_sampled_fallback(monkeypatch):
    """Engine with KAKVEDA_SERVE_SPEC: greedy traffic goes through verify
    chunks (spec stats move) with exact solo parity; a sampled request
    flips the pool to plain chunks and still completes."""
    from concurrent.futures import ThreadPoolExecutor

    monkeypatch.setenv("KAKVEDA_SERVE_SPEC", "4")
    params = init_params(jax.random.PRNGKey(0), CFG)
    solo = _solo(params, CFG)
    eng = ServingEngine(params, CFG, batch_slots=2, max_len=128, chunk_steps=4)
    try:
        assert eng.cb.spec_k == 4
        with ThreadPoolExecutor(max_workers=len(PROMPTS)) as ex:
            outs = list(ex.map(lambda p: eng.generate_ids(p, 12), PROMPTS))
        assert outs == solo
        assert eng.cb.spec_stats["chunks"] > 0
        sampled = eng.generate_ids([5, 6, 7], 8, temperature=0.9)
        assert len(sampled) >= 1
    finally:
        eng.close()


def test_all_levers_compose():
    """Spec verify chunks + prefix caching + int8 KV + streaming callbacks
    in ONE engine, exact parity with solo decode — the composite a real
    deployment would run (judge traffic: shared template head, greedy,
    quantized cache, streamed to the UI)."""
    cfg = LlamaConfig(
        vocab_size=264, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, dtype=jnp.float32, kv_quant="int8",
    )
    params = init_params(jax.random.PRNGKey(5), cfg)
    head = list(range(40, 56))
    prompts = [head + [5, 6], head + list(range(80, 95)), head]
    solo = [
        generate_tokens(params, cfg, p, max_new_tokens=10, max_len=128) for p in prompts
    ]
    streamed = {i: [] for i in range(len(prompts))}
    cb = ContinuousBatcher(params, cfg, batch_slots=2, max_len=128, chunk_steps=4, spec_k=4)
    assert cb.register_prefix(head)
    rids = {}
    pending = list(enumerate(prompts))
    while pending or cb.slots:
        while pending and cb.free:
            i, p = pending.pop(0)
            rids[cb.admit(
                p, max_new_tokens=10,
                on_tokens=(lambda i: lambda new, done: streamed[i].extend(new))(i),
            )] = i
        cb.step()  # dispatches spec (greedy pool)
    outs = [None] * len(prompts)
    for rid, i in rids.items():
        outs[i] = cb.results[rid]
        assert streamed[i] == cb.results[rid]
    assert outs == solo
    assert cb.spec_stats["chunks"] > 0
    assert cb.prefix_stats["hits"] == len(prompts)


def test_spec_streaming_callbacks():
    """on_tokens fires per verify chunk with the accepted tokens."""
    params = init_params(jax.random.PRNGKey(0), CFG)
    got, flags = [], []
    cb = ContinuousBatcher(params, CFG, batch_slots=1, max_len=128, chunk_steps=4, spec_k=4)
    rid = cb.admit(
        [5, 6, 7], max_new_tokens=10,
        on_tokens=lambda new, done: (got.extend(new), flags.append(done)),
    )
    while cb.slots:
        cb.step_spec()
    assert got == cb.results[rid]
    assert flags[-1] is True
