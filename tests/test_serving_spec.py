"""Speculative decoding inside the continuous-batching engine: each chunk
verifies k host-drafted tokens in ONE forward, advancing greedy slots
1..k+1 tokens per weight stream — with outputs TOKEN-IDENTICAL to the
plain chunked path (accepted drafts equal their own greedy verdicts by
construction; corrections are greedy).

Decode is weight-bandwidth-bound, so the k+1-wide verify rides the same
weight stream as a 1-wide step; on repetitive traffic (judge templates,
citation lists) acceptance multiplies tokens/stream. KAKVEDA_SERVE_SPEC=k
enables it on the engine; sampled slots fall back to plain chunks.
"""

import jax
import jax.numpy as jnp
import numpy as np

from kakveda_tpu.models.generate import generate_tokens
from kakveda_tpu.models.llama import LlamaConfig, init_params
from kakveda_tpu.models.serving import ContinuousBatcher, ServingEngine

CFG = LlamaConfig(
    vocab_size=264, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq_len=128, dtype=jnp.float32,
)

PROMPTS = [[5, 6, 7], [10, 11, 12, 13, 14], [42], [9, 8]]


def _solo(params, cfg, n=12):
    return [
        generate_tokens(params, cfg, p, max_new_tokens=n, max_len=128) for p in PROMPTS
    ]


def test_spec_chunk_parity_multi_slot():
    """run_all's step() dispatches to verify chunks for a greedy pool."""
    params = init_params(jax.random.PRNGKey(0), CFG)
    solo = _solo(params, CFG)
    cb = ContinuousBatcher(params, CFG, batch_slots=2, max_len=128, chunk_steps=4, spec_k=4)
    assert cb.run_all(PROMPTS, max_new_tokens=12) == solo
    assert cb.spec_stats["chunks"] > 0
    # Every chunk emits at least one token per active slot.
    assert cb.spec_stats["emitted"] >= cb.spec_stats["slot_chunks"]


def test_draft_lookup_semantics():
    """The host draft heuristic itself (acceptance-neutral to parity, so
    only a direct test catches a shift bug that would silently collapse
    the speculative win): longest recent suffix match, copy SHIFTED by
    one (the copy's first token is the t0 analog, not a draft)."""
    d = ContinuousBatcher._draft
    # History "A B C x ... A B C" — 3-token suffix matches at j=2; the
    # t0 analog is hist[3] (=9), drafts start at hist[4].
    hist = [7, 8, 3, 9, 4, 5, 7, 8, 3]
    assert d(hist, 4) == [4, 5, 7, 8]
    # Single-token match only: last token 3 occurred at j=1; t0 analog is
    # hist[2], drafts from hist[3].
    hist2 = [1, 3, 6, 2, 5, 3]
    assert d(hist2, 3) == [2, 5, 3]
    # Longest match preferred over a more recent shorter one: suffix
    # [8, 3] matches ending at j=2 even though a later lone 3 sits at
    # j=4; the t0 analog is hist[3] (=1), drafts start at hist[4].
    hist3 = [9, 8, 3, 1, 3, 2, 8, 3]
    assert d(hist3, 2) == [3, 2]
    # No earlier occurrence / degenerate history: PAD drafts.
    assert d([1, 2, 3], 3) == [0, 0, 0]
    assert d([5], 2) == [0, 0]
    assert d([], 2) == [0, 0]
    # Copy region running off the end extrapolates PERIODICALLY (period =
    # anchor distance): [4, 6] tiles forward instead of padding.
    hist4 = [4, 6, 4]
    assert d(hist4, 4) == [4, 6, 4, 6]


def test_draft_period1_not_degenerate():
    """A trailing same-token run used to anchor at j=n-2 with an empty
    copy region — all-PAD drafts, zero acceptance on exactly the most
    repetitive traffic speculation targets. Periodic extrapolation tiles
    the run (period 1) instead."""
    d = ContinuousBatcher._draft
    assert d([5, 5, 5, 5, 5], 4) == [5, 5, 5, 5]
    assert d([9, 3, 7, 7, 7], 3) == [7, 7, 7]
    # Period-2 loop drafts its own continuation.
    assert d([1, 2, 1, 2, 1, 2], 3) == [2, 1, 2]


def test_spec_acceptance_on_repetitive_traffic():
    """A model that settles into an argmax loop must accept drafts:
    emitted tokens per slot-chunk > 1 on average (the spec win exists).
    This seed's output ends in a period-1 constant run — the exact case
    the old suffix lookup degenerated to all-PAD drafts on (anchoring at
    j=n-2 left an empty copy region; periodic extrapolation tiles the
    run instead), which left this assertion failing at rate == 1.0."""
    params = init_params(jax.random.PRNGKey(2), CFG)
    p = [7, 8, 9, 7, 8, 9, 7, 8, 9]
    solo = generate_tokens(params, CFG, p, max_new_tokens=40, max_len=128)
    assert solo[-4:] == [solo[-1]] * 4  # the period-1 regime is real
    cb = ContinuousBatcher(params, CFG, batch_slots=1, max_len=128, chunk_steps=4, spec_k=4)
    rid = cb.admit(p, max_new_tokens=40)
    while cb.slots:
        cb.step_spec()
    assert cb.results[rid] == solo
    rate = cb.spec_stats["emitted"] / cb.spec_stats["slot_chunks"]
    assert rate > 1.3, cb.spec_stats
    assert cb.spec_stats["accepted"] > 0
    # Adaptive k recovered to the ceiling inside the constant run.
    assert max(cb.spec_stats["k_trace"]) == 4


def test_spec_parity_int8_kv():
    cfg = LlamaConfig(
        vocab_size=264, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, dtype=jnp.float32, kv_quant="int8",
    )
    params = init_params(jax.random.PRNGKey(2), cfg)
    solo = _solo(params, cfg, n=8)
    cb = ContinuousBatcher(params, cfg, batch_slots=2, max_len=128, chunk_steps=4, spec_k=4)
    assert cb.run_all(PROMPTS, max_new_tokens=8) == solo


def test_spec_parity_sliding_window():
    cfg = LlamaConfig(
        vocab_size=264, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, dtype=jnp.float32, sliding_window=12,
    )
    params = init_params(jax.random.PRNGKey(3), cfg)
    solo = _solo(params, cfg, n=10)
    cb = ContinuousBatcher(params, cfg, batch_slots=2, max_len=128, chunk_steps=4, spec_k=4)
    assert cb.run_all(PROMPTS, max_new_tokens=10) == solo


def test_engine_spec_greedy_and_sampled_fallback(monkeypatch):
    """Engine with KAKVEDA_SERVE_SPEC: greedy traffic goes through verify
    chunks (spec stats move) with exact solo parity; a sampled request
    flips the pool to plain chunks and still completes."""
    from concurrent.futures import ThreadPoolExecutor

    monkeypatch.setenv("KAKVEDA_SERVE_SPEC", "4")
    params = init_params(jax.random.PRNGKey(0), CFG)
    solo = _solo(params, CFG)
    eng = ServingEngine(params, CFG, batch_slots=2, max_len=128, chunk_steps=4)
    try:
        assert eng.cb.spec_k == 4
        with ThreadPoolExecutor(max_workers=len(PROMPTS)) as ex:
            outs = list(ex.map(lambda p: eng.generate_ids(p, 12), PROMPTS))
        assert outs == solo
        assert eng.cb.spec_stats["chunks"] > 0
        sampled = eng.generate_ids([5, 6, 7], 8, temperature=0.9)
        assert len(sampled) >= 1
    finally:
        eng.close()


def test_all_levers_compose():
    """Spec verify chunks + prefix caching + int8 KV + streaming callbacks
    in ONE engine, exact parity with solo decode — the composite a real
    deployment would run (judge traffic: shared template head, greedy,
    quantized cache, streamed to the UI)."""
    cfg = LlamaConfig(
        vocab_size=264, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, dtype=jnp.float32, kv_quant="int8",
    )
    params = init_params(jax.random.PRNGKey(5), cfg)
    head = list(range(40, 56))
    prompts = [head + [5, 6], head + list(range(80, 95)), head]
    solo = [
        generate_tokens(params, cfg, p, max_new_tokens=10, max_len=128) for p in prompts
    ]
    streamed = {i: [] for i in range(len(prompts))}
    cb = ContinuousBatcher(params, cfg, batch_slots=2, max_len=128, chunk_steps=4, spec_k=4)
    assert cb.register_prefix(head)
    rids = {}
    pending = list(enumerate(prompts))
    while pending or cb.slots:
        while pending and cb.free:
            i, p = pending.pop(0)
            rids[cb.admit(
                p, max_new_tokens=10,
                on_tokens=(lambda i: lambda new, done: streamed[i].extend(new))(i),
            )] = i
        cb.step()  # dispatches spec (greedy pool)
    outs = [None] * len(prompts)
    for rid, i in rids.items():
        outs[i] = cb.results[rid]
        assert streamed[i] == cb.results[rid]
    assert outs == solo
    assert cb.spec_stats["chunks"] > 0
    assert cb.prefix_stats["hits"] == len(prompts)


def test_spec_streaming_callbacks():
    """on_tokens fires per verify chunk with the accepted tokens."""
    params = init_params(jax.random.PRNGKey(0), CFG)
    got, flags = [], []
    cb = ContinuousBatcher(params, CFG, batch_slots=1, max_len=128, chunk_steps=4, spec_k=4)
    rid = cb.admit(
        [5, 6, 7], max_new_tokens=10,
        on_tokens=lambda new, done: (got.extend(new), flags.append(done)),
    )
    while cb.slots:
        cb.step_spec()
    assert got == cb.results[rid]
    assert flags[-1] is True


# ---------------------------------------------------------------------------
# Acceptance auto-gate, per-slot adaptive k, and pipelined verify chunks.
# ---------------------------------------------------------------------------


def _drain_pipelined_spec(cb, prompts, max_new=12):
    """The ServingEngine's pipelined ordering, inline: dispatch verify
    chunk i+1 before fetching chunk i's acceptance; drain before any
    admission; fall back to pipelined plain chunks when spec_ready()
    says so (sampled slot or gate off)."""
    pending = list(enumerate(prompts))
    order, handle, spec_handle = {}, None, None
    while pending or cb.slots or handle is not None or spec_handle is not None:
        if pending and cb.free and spec_handle is not None:
            cb.process_spec_chunk(spec_handle)
            spec_handle = None
        while pending and cb.free:
            i, p = pending.pop(0)
            order[cb.admit(p, max_new_tokens=max_new)] = i
        if cb.spec_ready():
            cb.process_chunk(handle)
            handle = None
            if spec_handle is not None and cb.spec_pipeline_ready():
                nxt = cb.step_spec_async()
                cb.process_spec_chunk(spec_handle)
                spec_handle = nxt
            else:
                cb.process_spec_chunk(spec_handle)
                spec_handle = None
                if cb.slots and cb.spec_ready():
                    spec_handle = cb.step_spec_async()
        elif cb.slots:
            cb.process_spec_chunk(spec_handle)
            spec_handle = None
            nxt = cb.step_async()
            cb.process_chunk(handle)
            handle = nxt
        else:
            cb.process_chunk(handle)
            cb.process_spec_chunk(spec_handle)
            handle = spec_handle = None
    outs = [None] * len(prompts)
    for rid, i in order.items():
        outs[i] = cb.results.pop(rid)
    return outs


def test_pipelined_spec_parity(monkeypatch):
    """Verify chunk i+1 dispatched before chunk i's acceptance reaches
    the host (device-threaded slot_pos, cursor drafts) stays token-
    identical to solo decode — including across retire/admit boundaries
    where the pipeline must drain and resync from host mirrors."""
    monkeypatch.setenv("KAKVEDA_SERVE_SPEC_CALIB", "0")
    monkeypatch.setenv("KAKVEDA_SERVE_SPEC_BREAKEVEN", "0")  # gate stays open
    params = init_params(jax.random.PRNGKey(0), CFG)
    solo = _solo(params, CFG)
    cb = ContinuousBatcher(params, CFG, batch_slots=2, max_len=128, chunk_steps=4, spec_k=4)
    assert _drain_pipelined_spec(cb, PROMPTS) == solo
    assert cb.spec_stats["chunks"] > 0
    assert cb._spec_pending == 0  # pipeline fully drained


def test_pipelined_spec_cursor_continues_accepted_run(monkeypatch):
    """On a period-1 pool the pipelined path must KEEP accepting: the
    cursor extends the in-flight chunk's predicted emission, so full-
    accept chunks chain without the host ever seeing the previous chunk
    first (the acceptance-preserving half of the pipeline win)."""
    monkeypatch.setenv("KAKVEDA_SERVE_SPEC_CALIB", "0")
    monkeypatch.setenv("KAKVEDA_SERVE_SPEC_BREAKEVEN", "0")
    params = init_params(jax.random.PRNGKey(2), CFG)
    p = [7, 8, 9, 7, 8, 9, 7, 8, 9]
    solo = generate_tokens(params, CFG, p, max_new_tokens=40, max_len=128)
    cb = ContinuousBatcher(params, CFG, batch_slots=1, max_len=128, chunk_steps=4, spec_k=4)
    assert _drain_pipelined_spec(cb, [p], max_new=40) == [solo]
    s = cb.spec_stats
    assert s["emitted"] / s["slot_chunks"] > 1.3, s
    assert s["accepted"] > 0


def test_gate_disables_spec_on_low_acceptance(monkeypatch):
    """A pool whose acceptance can't clear break-even must turn itself
    OFF after warmup and decode plain — parity intact, later chunks are
    plain chunks (no more configured slowdowns)."""
    monkeypatch.setenv("KAKVEDA_SERVE_SPEC_CALIB", "0")
    monkeypatch.setenv("KAKVEDA_SERVE_SPEC_WARMUP", "2")
    monkeypatch.setenv("KAKVEDA_SERVE_SPEC_BREAKEVEN", "1000")  # unreachable
    params = init_params(jax.random.PRNGKey(0), CFG)
    solo = _solo(params, CFG)
    cb = ContinuousBatcher(params, CFG, batch_slots=2, max_len=128, chunk_steps=4, spec_k=4)
    assert cb.run_all(PROMPTS, max_new_tokens=12) == solo
    assert cb.spec_stats["gate_state"] == "off"
    assert cb.spec_stats["chunks"] >= 2  # warmup spec chunks ran
    spec_chunks_at_off = cb.spec_stats["chunks"]
    assert len(cb._plain_walls) > 0  # post-gate decoding went plain
    # A second drain on the gated-off pool runs NO spec chunks at all.
    assert cb.run_all(PROMPTS, max_new_tokens=12) == solo
    assert cb.spec_stats["chunks"] == spec_chunks_at_off


def test_gate_keeps_spec_on_high_acceptance(monkeypatch):
    """The opposite verdict: acceptance above break-even keeps the gate
    ON through warmup (speculation stays enabled for the pool)."""
    monkeypatch.setenv("KAKVEDA_SERVE_SPEC_CALIB", "0")
    monkeypatch.setenv("KAKVEDA_SERVE_SPEC_WARMUP", "2")
    monkeypatch.setenv("KAKVEDA_SERVE_SPEC_BREAKEVEN", "0")
    params = init_params(jax.random.PRNGKey(2), CFG)
    p = [7, 8, 9, 7, 8, 9, 7, 8, 9]
    cb = ContinuousBatcher(params, CFG, batch_slots=1, max_len=128, chunk_steps=4, spec_k=4)
    cb.run_all([p], max_new_tokens=40)
    assert cb.spec_stats["gate_state"] == "on"
    assert cb.spec_stats["tokens_per_verify"] > 1.0


def test_gate_reprobe_reenters_warmup(monkeypatch):
    """An OFF gate re-probes after KAKVEDA_SERVE_SPEC_REPROBE plain
    chunks: traffic may have turned repetitive, and warmup (with a
    hysteresis margin) re-measures instead of staying off forever."""
    monkeypatch.setenv("KAKVEDA_SERVE_SPEC_CALIB", "0")
    monkeypatch.setenv("KAKVEDA_SERVE_SPEC_WARMUP", "1")
    monkeypatch.setenv("KAKVEDA_SERVE_SPEC_BREAKEVEN", "1000")
    monkeypatch.setenv("KAKVEDA_SERVE_SPEC_REPROBE", "2")
    params = init_params(jax.random.PRNGKey(0), CFG)
    cb = ContinuousBatcher(params, CFG, batch_slots=2, max_len=128, chunk_steps=4, spec_k=4)
    cb.run_all(PROMPTS, max_new_tokens=12)
    spec_chunks = cb.spec_stats["chunks"]
    assert spec_chunks >= 1
    # Another drain: the re-probe window re-opens the gate to warmup and
    # spec chunks run again (then the unreachable break-even closes it).
    cb.run_all(PROMPTS, max_new_tokens=12)
    assert cb.spec_stats["chunks"] > spec_chunks


def test_adaptive_k_shrinks_on_rejection(monkeypatch):
    """A slot whose drafts keep missing halves its draft width toward 1
    (the k trace ends narrow), so dead speculation stops paying host
    drafting and verify width."""
    monkeypatch.setenv("KAKVEDA_SERVE_SPEC_CALIB", "0")
    monkeypatch.setenv("KAKVEDA_SERVE_SPEC_BREAKEVEN", "0")
    params = init_params(jax.random.PRNGKey(1), CFG)
    p = [7, 8, 9, 7, 8, 9, 7, 8, 9]  # this seed's output does NOT loop
    cb = ContinuousBatcher(params, CFG, batch_slots=1, max_len=128, chunk_steps=4, spec_k=4)
    cb.run_all([p], max_new_tokens=24)
    kt = cb.spec_stats["k_trace"]
    assert kt[0] == 4 and 1 in kt, kt


def test_cancel_during_inflight_verify_chunk():
    """cancel_request between step_spec_async and process_spec_chunk: the
    stale snapshot must skip the cancelled slot (done-flag first), the
    survivor keeps exact solo parity, and the freed slot re-admits
    cleanly after the pipeline drains."""
    params = init_params(jax.random.PRNGKey(0), CFG)
    p_keep, p_cancel = [10, 11, 12, 13, 14], [5, 6, 7]
    solo_keep = generate_tokens(params, CFG, p_keep, max_new_tokens=12, max_len=128)
    cb = ContinuousBatcher(params, CFG, batch_slots=2, max_len=128, chunk_steps=4, spec_k=4)
    rid_c = cb.admit(p_cancel, max_new_tokens=12)
    rid_k = cb.admit(p_keep, max_new_tokens=12)
    h = cb.step_spec_async()
    got = cb.cancel_request(rid_c)
    assert got == []  # nothing emitted yet
    finished = cb.process_spec_chunk(h)
    assert rid_c not in finished
    while cb.slots:
        cb.step_spec()
    assert cb.results[rid_k] == solo_keep
    # Freed slot is reusable and the re-admitted request is exact too.
    rid2 = cb.admit(p_cancel, max_new_tokens=8)
    while cb.slots:
        cb.step_spec()
    assert cb.results[rid2] == generate_tokens(
        params, CFG, p_cancel, max_new_tokens=8, max_len=128
    )


def test_admit_refused_while_verify_chunk_in_flight():
    """Admission with an un-processed verify chunk would race the
    device-threaded slot_pos — it must refuse loudly, and succeed after
    the handle is processed."""
    import pytest

    params = init_params(jax.random.PRNGKey(0), CFG)
    cb = ContinuousBatcher(params, CFG, batch_slots=2, max_len=128, chunk_steps=4, spec_k=4)
    cb.admit([5, 6, 7], max_new_tokens=8)
    h = cb.step_spec_async()
    with pytest.raises(RuntimeError, match="in flight"):
        cb.admit([1, 2, 3], max_new_tokens=8)
    cb.process_spec_chunk(h)
    cb.admit([1, 2, 3], max_new_tokens=8)
    while cb.slots:
        cb.step_spec()


def test_prefix_slab_drafting():
    """A slot whose own history has NO anchor defers to a registered
    prefix's n-gram index: template spans draft from the slab corpus
    (the cross-corpus fallback) with literal, non-cyclic copies — so
    template traffic drafts continuations its short history has never
    emitted."""
    params = init_params(jax.random.PRNGKey(5), CFG)
    head = list(range(40, 56))
    cb = ContinuousBatcher(params, CFG, batch_slots=1, max_len=128, chunk_steps=4, spec_k=4)
    assert cb.register_prefix(head)
    # No token repeats inside this prompt → no self-anchor; the (43, 44)
    # bigram exists only in the registered head.
    cb.admit([7, 43, 44], max_new_tokens=8)
    st = list(cb.slots.values())[0]
    drafts, cursor, pred = cb._draft_slot(st, 4)
    assert st.index.anchor == (-1, 0)  # no self-anchor: prefix corpus answered
    # The head continues (43, 44) with 45, 46, ... — pred[0] is the t0
    # analog, drafts follow it.
    assert pred == [45, 46, 47, 48, 49]
    assert drafts == [46, 47, 48, 49]
    assert cursor is not None
    while cb.slots:
        cb.step_spec()
