"""Speculative decoding (models/speculative.py): exact greedy parity with
the plain decode loop — on repetitive prompts (high acceptance), random
prompts (low acceptance), converted HF checkpoints, int8 trees, and MoE
configs — plus round-count evidence that acceptance actually amortizes."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kakveda_tpu.models.generate import generate_tokens
from kakveda_tpu.models.llama import LlamaConfig, init_params
from kakveda_tpu.models.speculative import generate_tokens_speculative

CFG = LlamaConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=48, max_seq_len=256, dtype=jnp.float32,
)


@pytest.mark.parametrize("k", [1, 3, 4])
@pytest.mark.parametrize(
    "prompt",
    [
        list(range(5, 25)),                       # arbitrary
        [7, 8, 9, 10] * 6,                        # periodic — lookup should hit
        [3, 3, 3, 3, 3, 3, 3, 3],                 # degenerate repetition
        [11, 12],                                 # shorter than a draft window
    ],
)
def test_speculative_matches_plain_greedy(prompt, k):
    params = init_params(jax.random.PRNGKey(0), CFG)
    want = generate_tokens(params, CFG, prompt, max_new_tokens=24)
    got = generate_tokens_speculative(params, CFG, prompt, max_new_tokens=24, k=k)
    assert got == want, (got, want)


def test_speculative_matches_on_hf_checkpoint(tmp_path):
    transformers = pytest.importorskip("transformers")
    torch = pytest.importorskip("torch")
    hf_cfg = transformers.LlamaConfig(
        vocab_size=250,  # not a multiple of 8 → exercises effective_vocab mask
        hidden_size=64, intermediate_size=128, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=128,
    )
    torch.manual_seed(0)
    transformers.LlamaForCausalLM(hf_cfg).eval().save_pretrained(
        str(tmp_path), safe_serialization=True
    )
    from kakveda_tpu.models.hf_convert import load_hf_checkpoint

    params, cfg = load_hf_checkpoint(str(tmp_path), param_dtype=jnp.float32)
    prompt = list(range(5, 20))
    want = generate_tokens(params, cfg, prompt, max_new_tokens=16)
    got = generate_tokens_speculative(params, cfg, prompt, max_new_tokens=16, k=4)
    assert got == want


def test_speculative_int8_and_moe():
    from kakveda_tpu.models.quant import quantize_params_int8

    params = init_params(jax.random.PRNGKey(1), CFG)
    qparams = quantize_params_int8(params)
    prompt = [5, 6, 7, 8, 5, 6, 7, 8, 5, 6]
    assert generate_tokens_speculative(qparams, CFG, prompt, max_new_tokens=12) == \
        generate_tokens(qparams, CFG, prompt, max_new_tokens=12)

    moe_cfg = LlamaConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=48, max_seq_len=256, dtype=jnp.float32,
        n_experts=4, n_experts_per_tok=2,
    )
    mparams = init_params(jax.random.PRNGKey(2), moe_cfg)
    assert generate_tokens_speculative(mparams, moe_cfg, prompt, max_new_tokens=12) == \
        generate_tokens(mparams, moe_cfg, prompt, max_new_tokens=12)


def test_speculative_respects_context_window():
    """A prompt near cfg.max_seq_len must truncate the generation at the
    window (same prefix as plain greedy), never decode past it; a prompt
    with no room at all raises."""
    import dataclasses

    cfg = dataclasses.replace(CFG, max_seq_len=64)
    params = init_params(jax.random.PRNGKey(4), CFG)
    prompt = list(range(5, 45))  # 40 tokens in a 64 window
    plain = generate_tokens(params, cfg, prompt, max_new_tokens=100)
    spec = generate_tokens_speculative(params, cfg, prompt, max_new_tokens=100, k=4)
    assert len(spec) <= len(plain) <= 64 - len(prompt)
    assert spec == plain[: len(spec)]

    with pytest.raises(ValueError, match="room"):
        generate_tokens_speculative(params, cfg, list(range(3, 62)), max_new_tokens=8, k=4)


def test_pp_place_stacked_int8():
    """Stage-stacked int8 trees place on the pp mesh (specs derive from the
    actual structure, not the float layout)."""
    from jax.sharding import PartitionSpec as P

    from kakveda_tpu.models.pipeline import place_stacked, split_stages
    from kakveda_tpu.models.quant import quantize_params_int8
    from kakveda_tpu.parallel.mesh import create_mesh

    params = quantize_params_int8(init_params(jax.random.PRNGKey(5), CFG))
    mesh = create_mesh("pp:2")
    stacked = place_stacked(split_stages(params, CFG, 2), CFG, mesh)
    assert stacked["stages"]["wq"]["q"].sharding.spec == P("pp")
    assert stacked["stages"]["wq"]["s"].sharding.spec == P("pp")


def test_runtime_spec_mode_matches_chunked(monkeypatch):
    """KAKVEDA_SPEC=1 routes LlamaRuntime.generate through the speculative
    path with identical text and a tokens_per_round meta field."""
    from kakveda_tpu.models.generate import LlamaRuntime

    cfg = LlamaConfig(
        vocab_size=264, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=48, max_seq_len=256, dtype=jnp.float32,
    )
    rt = LlamaRuntime(cfg=cfg, seed=0)
    monkeypatch.delenv("KAKVEDA_SPEC", raising=False)
    plain = rt.generate("hello failure world", max_tokens=16)
    monkeypatch.setenv("KAKVEDA_SPEC", "1")
    spec = rt.generate("hello failure world", max_tokens=16)
    assert spec.text == plain.text
    assert spec.meta["speculative"] is True and spec.meta["tokens_per_round"] >= 1.0
    assert "speculative" not in plain.meta


def test_speculative_eos_truncation():
    params = init_params(jax.random.PRNGKey(3), CFG)
    prompt = list(range(5, 15))
    plain = generate_tokens(params, CFG, prompt, max_new_tokens=20)
    # pick the 5th generated token as a fake EOS: both paths must stop there
    eos = plain[5]
    want = generate_tokens(params, CFG, prompt, max_new_tokens=20, eos_id=eos)
    got = generate_tokens_speculative(params, CFG, prompt, max_new_tokens=20, eos_id=eos)
    assert got == want


def test_acceptance_amortizes_on_forced_repetition():
    """A model trained into a short loop must emit well over one token per
    verify round (each round = one weight stream): train a tiny model to
    reproduce a strict 4-token cycle, then check both exact parity on the
    long periodic generation AND the measured tokens/round."""
    from kakveda_tpu.models.train import fit

    corpus = "abcd" * 200
    cfg = LlamaConfig(
        vocab_size=264, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=48, max_seq_len=256, dtype=jnp.float32,
    )
    params, losses = fit(cfg, corpus, steps=60, batch=2, seq_len=32, lr=5e-3, log_every=0)
    assert losses[-1] < losses[0]
    from kakveda_tpu.models.tokenizer import ByteTokenizer

    prompt = ByteTokenizer().encode("abcdabcdabcd")
    want = generate_tokens(params, cfg, prompt, max_new_tokens=40)
    got, stats = generate_tokens_speculative(
        params, cfg, prompt, max_new_tokens=40, k=4, return_stats=True
    )
    assert got == want
    # The trained model settles into a periodic generation (deterministic
    # seeds), so the bigram lookup hits nearly every round: measured 5.0
    # tokens/round (= perfect k+1 acceptance) at these seeds.
    assert stats["tokens_per_round"] > 2.0, stats
    assert stats["rounds"] <= 40
