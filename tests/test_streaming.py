"""Streaming generation: engine on_tokens callbacks, runtime text-delta
generator, and the playground SSE endpoint.

Beyond-reference capability: the reference's playground blocks on one full
Ollama reply per request (services/dashboard/app.py:3127-3299); here text
deltas reach the client per decode chunk, token-identical to the blocking
path.
"""

import asyncio
import json

import jax
import jax.numpy as jnp
import pytest
from aiohttp.test_utils import TestClient, TestServer

from kakveda_tpu.models.generate import LlamaRuntime, generate_tokens
from kakveda_tpu.models.llama import LlamaConfig, init_params
from kakveda_tpu.models.serving import ContinuousBatcher, ServingEngine

CFG = LlamaConfig(
    vocab_size=264, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq_len=128, dtype=jnp.float32,
)


def test_batcher_on_tokens_streams_exact_results():
    """Chunk callbacks deliver exactly the tokens the blocking result
    carries, in order, with done=True on the final chunk."""
    params = init_params(jax.random.PRNGKey(0), CFG)
    prompts = [[5, 6, 7], [10, 11, 12, 13]]
    streamed = {0: [], 1: []}
    flags = {0: [], 1: []}

    cb = ContinuousBatcher(params, CFG, batch_slots=2, max_len=64, chunk_steps=4)
    rids = [
        cb.admit(
            p, max_new_tokens=10,
            on_tokens=(lambda i: lambda new, done: (streamed[i].extend(new), flags[i].append(done)))(i),
        )
        for i, p in enumerate(prompts)
    ]
    while cb.active:
        cb.step()
    for i, rid in enumerate(rids):
        assert streamed[i] == cb.results[rid]
        assert flags[i][-1] is True
        assert all(f is False for f in flags[i][:-1])


def test_engine_stream_callback_runs_on_loop():
    params = init_params(jax.random.PRNGKey(0), CFG)
    got = []
    eng = ServingEngine(params, CFG, batch_slots=2, max_len=64, chunk_steps=4)
    try:
        fut = eng.submit([5, 6, 7], 8, on_tokens=lambda new, done: got.extend(new))
        result = fut.result(timeout=120)
        assert got == result
    finally:
        eng.close()


def test_engine_cancel_frees_slot_midflight():
    """cancel() on a mid-decode request resolves its Future with the
    partial tokens and frees the slot for new traffic; a later request
    still gets exact solo parity (the cancelled slot's rows are masked
    and overwritten like any retired slot's)."""
    import time as _time

    from kakveda_tpu.models.generate import generate_tokens

    params = init_params(jax.random.PRNGKey(0), CFG)
    solo = generate_tokens(params, CFG, [9, 8, 7], max_new_tokens=10, max_len=64)
    eng = ServingEngine(params, CFG, batch_slots=1, max_len=64, chunk_steps=2)
    try:
        fut = eng.submit([5, 6, 7], 40)
        for _ in range(200):  # wait until it is actually decoding
            if eng.cb.active:
                break
            _time.sleep(0.05)
        eng.cancel(fut)
        partial = fut.result(timeout=60)
        assert len(partial) < 40  # stopped early, partial tokens returned
        # The freed slot serves the next request with exact parity.
        assert eng.generate_ids([9, 8, 7], 10) == solo
    finally:
        eng.close()


def test_engine_cancel_queued_request():
    """Cancelling a request still waiting for a slot cancels its Future
    outright and it is never admitted."""
    from concurrent.futures import CancelledError

    params = init_params(jax.random.PRNGKey(0), CFG)
    eng = ServingEngine(params, CFG, batch_slots=1, max_len=64, chunk_steps=2)
    try:
        first = eng.submit([5, 6, 7], 30)  # occupies the only slot
        waiting = eng.submit([1, 2, 3], 30)
        eng.cancel(waiting)
        with pytest.raises(CancelledError):
            waiting.result(timeout=60)
        assert len(first.result(timeout=120)) > 0  # the running one completes
        assert eng.stats()["completed"] == 1
    finally:
        eng.close()


def test_generate_stream_cancel_before_first_token(monkeypatch):
    """A streaming request still WAITING for a slot (pool full, zero
    deltas delivered) cancels promptly when the consumer sets the cancel
    event — it must not sit until its first token arrives."""
    import threading
    import time as _time

    from kakveda_tpu.models.generate import LlamaRuntime

    monkeypatch.setenv("KAKVEDA_SERVE_CONTINUOUS", "1")
    monkeypatch.setenv("KAKVEDA_SERVE_SLOTS", "1")
    rt = LlamaRuntime(cfg=CFG, seed=0)
    try:
        eng = rt.engine()
        blocker = eng.submit([5, 6, 7], 40)  # occupies the only slot
        cancel_ev = threading.Event()
        got: list = []

        def consume():
            for d in rt.generate_stream("queued then abandoned", max_tokens=10, cancel=cancel_ev):
                got.append(d)

        t = threading.Thread(target=consume)
        t.start()
        _time.sleep(1.0)  # let it enqueue behind the blocker
        cancel_ev.set()
        t.join(timeout=30)
        assert not t.is_alive(), "stream consumer still blocked after cancel"
        assert got == []  # never produced a token
        assert len(blocker.result(timeout=120)) > 0  # slot owner unaffected
    finally:
        rt.retire()


@pytest.mark.parametrize("seed,spec_k", [(0, 0), (1, 0), (2, 4), (3, 4)])
def test_engine_randomized_submit_cancel_stress(seed, spec_k):
    """Randomized interleaving of submits and cancels against the live
    engine: every Future must resolve (result or CancelledError), the
    slot pool must fully drain (free == B), and accounting must balance.
    The slot-reuse/cancel/pipelining interactions this shakes out are
    exactly the ones a deterministic test can't enumerate."""
    import random
    from concurrent.futures import CancelledError

    rng = random.Random(seed)
    params = init_params(jax.random.PRNGKey(0), CFG)
    eng = ServingEngine(params, CFG, batch_slots=2, max_len=64, chunk_steps=2, spec_k=spec_k)
    futs = []
    try:
        for _ in range(24):
            op = rng.random()
            if op < 0.7 or not futs:
                prompt = [rng.randrange(5, 250) for _ in range(rng.randrange(1, 9))]
                futs.append(eng.submit(prompt, rng.randrange(4, 24)))
            else:
                eng.cancel(rng.choice(futs))
            if rng.random() < 0.3:
                import time as _time

                _time.sleep(0.05)
        results = 0
        cancelled = 0
        for f in futs:
            try:
                toks = f.result(timeout=300)
                assert isinstance(toks, list)
                results += 1
            except CancelledError:
                cancelled += 1
        assert results + cancelled == len(futs)
        # Pool fully drained: every slot back on the free list.
        for _ in range(100):
            if len(eng.cb.free) == eng.cb.B and not eng.cb.slots:
                break
            import time as _time

            _time.sleep(0.1)
        assert len(eng.cb.free) == eng.cb.B and not eng.cb.slots
    finally:
        eng.close()


@pytest.mark.parametrize("continuous", ["1", "0"])
def test_runtime_generate_stream_matches_generate(monkeypatch, continuous):
    """Joined deltas equal the blocking generate() text on BOTH paths —
    engine streaming and the chunked solo fallback."""
    monkeypatch.setenv("KAKVEDA_SERVE_CONTINUOUS", continuous)
    rt = LlamaRuntime(cfg=CFG, seed=0)
    try:
        prompt = "stream parity check"
        blocking = rt.generate(prompt, max_tokens=12).text
        parts = list(rt.generate_stream(prompt, max_tokens=12))
        assert len(parts) >= 1
        assert "".join(parts) == blocking
    finally:
        rt.retire()


def test_playground_stream_sse(tmp_path, monkeypatch):
    """The SSE endpoint emits delta events then a done event, records the
    run, and the concatenated deltas equal the blocking response text."""
    from kakveda_tpu.dashboard.app import make_dashboard_app
    from kakveda_tpu.platform import Platform

    monkeypatch.setenv("KAKVEDA_SERVE_CONTINUOUS", "1")
    from kakveda_tpu.dashboard.core import RATE_LIMITER

    RATE_LIMITER._hits.clear()
    rt = LlamaRuntime(cfg=CFG, seed=0)
    plat = Platform(data_dir=tmp_path / "data", capacity=256, dim=1024)
    app = make_dashboard_app(platform=plat, db_path=tmp_path / "dash.db", model=rt)

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post(
                "/login",
                data={"email": "admin@local", "password": "admin123", "next": "/"},
                allow_redirects=False,
            )
            assert r.status == 302
            blocking = rt.generate("hello stream").text  # endpoint default max_tokens
            r = await client.post(
                "/playground/stream", data={"prompt": "hello stream", "target": "model"}
            )
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/event-stream")
            body = await r.text()
            events = [
                json.loads(line[len("data: "):])
                for line in body.splitlines()
                if line.startswith("data: ")
            ]
            assert events, body
            assert events[-1].get("done") is True
            text = "".join(e.get("delta", "") for e in events)
            assert text == blocking
            # The run landed in trace_runs like /playground/run does.
            r = await client.get("/runs?q=provider:tpu")
            assert r.status == 200
        finally:
            await client.close()

    asyncio.run(go())
    rt.retire()


def test_playground_stream_stub_and_fallback(tmp_path):
    """The stub runtime streams word-by-word (hermetic SSE demo), and a
    runtime WITHOUT generate_stream still streams via the one-delta
    fallback."""
    from kakveda_tpu.dashboard.app import make_dashboard_app
    from kakveda_tpu.dashboard.core import RATE_LIMITER
    from kakveda_tpu.models.runtime import StubRuntime
    from kakveda_tpu.platform import Platform

    class NoStream(StubRuntime):
        generate_stream = None  # simulate a runtime without streaming

    RATE_LIMITER._hits.clear()
    plat = Platform(data_dir=tmp_path / "data", capacity=256, dim=1024)
    app = make_dashboard_app(
        platform=plat, db_path=tmp_path / "dash.db", model=StubRuntime()
    )
    app2 = make_dashboard_app(
        platform=plat, db_path=tmp_path / "dash2.db", model=NoStream()
    )

    from kakveda_tpu.models.runtime import STUB_RESPONSE

    async def run_one(a):
        client = TestClient(TestServer(a))
        await client.start_server()
        try:
            r = await client.post(
                "/login",
                data={"email": "admin@local", "password": "admin123", "next": "/"},
                allow_redirects=False,
            )
            assert r.status == 302
            r = await client.post(
                "/playground/stream", data={"prompt": "please cite sources"}
            )
            assert r.status == 200
            events = [
                json.loads(line[len("data: "):])
                for line in (await r.text()).splitlines()
                if line.startswith("data: ")
            ]
            deltas = [e["delta"] for e in events if "delta" in e]
            assert events[-1].get("done") is True
            return deltas
        finally:
            await client.close()

    async def go():
        word_deltas = await run_one(app)
        assert len(word_deltas) > 1 and "".join(word_deltas) == STUB_RESPONSE
        fallback_deltas = await run_one(app2)
        assert len(fallback_deltas) == 1 and fallback_deltas[0] == STUB_RESPONSE

    asyncio.run(go())
