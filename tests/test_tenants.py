"""Per-tenant fairness & isolation (docs/robustness.md § multi-tenancy):
admission quotas with tenant provenance and the fail-open chaos site, the
warn micro-batcher's deficit-round-robin batch composition, the serving
engine's weighted-fair slot pick with its max-wait promotion starvation
bound, bounded tenant-state tables under key churn, the noisy-neighbor
scenario/SLO gates, and the chaos drill: an engine crash mid-flood must
not cost a victim its admission.

``KAKVEDA_TENANT_FAIR=0`` parity is asserted at every layer — the knob
resolves at construction, so the tests monkeypatch the env BEFORE building
the controller/batcher under test.

Global-state discipline: the admission controller and the promotions
counter are process-global, so every test resets them in teardown (the
same contract as tests/test_overload.py)."""

import asyncio
import time
from types import SimpleNamespace

import jax
import pytest

from kakveda_tpu.core import admission as adm_mod
from kakveda_tpu.core import faults
from kakveda_tpu.core.admission import (
    AdmissionController,
    BrownoutController,
    OverloadError,
)
from kakveda_tpu.core.ratelimit import TokenBucket
from kakveda_tpu.service.batcher import MicroBatcher


@pytest.fixture(autouse=True)
def _clean_globals():
    """Nothing armed, no global admission state, promotions at zero —
    before AND after every test in this file."""
    faults.disarm()
    adm_mod.reset_for_tests()
    yield
    faults.disarm()
    adm_mod.reset_for_tests()


def _adm(**limits):
    merged = {"warn": 4, "ingest": 1, "interactive": 4, "background": 1}
    merged.update(limits)
    return AdmissionController(
        limits=merged, enabled=True,
        brownout=BrownoutController(enabled=False),
    )


# ---------------------------------------------------------------------------
# admission quotas
# ---------------------------------------------------------------------------


def test_lone_tenant_uses_full_class_bound():
    """Work-conserving: the per-tenant share cap must NOT bind while no
    other tenant holds work — a lone tenant gets the whole class."""
    adm = _adm()
    for _ in range(4):  # warn limit 4, share cap would be 2
        adm.try_admit("warn", tenant="app-solo")
    with pytest.raises(OverloadError) as ei:
        adm.try_admit("warn", tenant="app-solo")
    # At the class bound the shed is queue_full, never tenant_quota.
    assert ei.value.reason == "queue_full"
    assert ei.value.tenant == "app-solo"
    for _ in range(4):
        adm.release("warn", tenant="app-solo")


def test_contended_tenant_quota_sheds_with_provenance():
    """With another tenant holding work, a tenant at its share cap sheds
    tenant_quota — typed, with tenant provenance and a Retry-After."""
    adm = _adm()  # warn=4, share 0.5 → cap 2
    adm.try_admit("warn", tenant="app-a")
    adm.try_admit("warn", tenant="app-a")
    adm.try_admit("warn", tenant="app-b")
    with pytest.raises(OverloadError) as ei:
        adm.try_admit("warn", tenant="app-a")
    assert ei.value.reason == "tenant_quota"
    assert ei.value.klass == "warn"
    assert ei.value.tenant == "app-a"
    assert ei.value.retry_after > 0
    assert adm.shed_counts().get("warn/tenant_quota") == 1
    info = adm.tenants_info()
    assert info["fair"] and info["table_size"] >= 2
    assert info["top_shed"][0]["tenant"] == "app-a"
    assert info["top_shed"][0]["sheds"] == 1
    # Release frees the quota: the same tenant admits again.
    adm.release("warn", tenant="app-a")
    adm.try_admit("warn", tenant="app-a")
    for t in ("app-a", "app-a", "app-b"):
        adm.release("warn", tenant=t)


def test_tenant_info_rides_admission_info():
    """info() carries the tenants block — the /readyz payload cli
    status/doctor read."""
    adm = _adm()
    adm.try_admit("warn", tenant="app-x")
    info = adm.info()
    assert info["tenants"]["table_size"] == 1
    adm.release("warn", tenant="app-x")


@pytest.mark.chaos
def test_tenant_quota_fault_fails_open():
    """The admission.tenant_quota site fails OPEN: armed, the quota check
    is skipped (degraded counter bumps) and the request admits on class
    capacity — degraded fairness, never a shed storm."""
    adm = _adm()
    degraded = adm._c_tenant_degraded._default()
    before = degraded.value
    adm.try_admit("warn", tenant="app-a")
    adm.try_admit("warn", tenant="app-a")
    adm.try_admit("warn", tenant="app-b")
    faults.arm("admission.tenant_quota:1:-1")
    adm.try_admit("warn", tenant="app-a")  # over share cap: admits anyway
    assert degraded.value == before + 1
    # The CLASS bound still holds even with the quota degraded.
    with pytest.raises(OverloadError) as ei:
        adm.try_admit("warn", tenant="app-b")
    assert ei.value.reason == "queue_full"
    for t in ("app-a", "app-a", "app-a", "app-b"):
        adm.release("warn", tenant=t)


def test_other_bucket_never_quota_sheds(monkeypatch):
    """When every table row is live (no idle victim to evict), a new
    tenant folds into the aggregate "other" bucket — which has no
    per-tenant resolution and therefore NEVER quota-sheds (fail open)."""
    monkeypatch.setenv("KAKVEDA_TENANT_TABLE", "2")
    adm = _adm()  # warn=4, share cap 2
    adm.try_admit("warn", tenant="app-a")
    adm.try_admit("warn", tenant="app-b")
    # Table full with live rows: app-c folds into "other" and may take
    # the remaining class slots without a tenant_quota shed.
    adm.try_admit("warn", tenant="app-c")
    adm.try_admit("warn", tenant="app-c")
    with pytest.raises(OverloadError) as ei:
        adm.try_admit("warn", tenant="app-c")
    assert ei.value.reason == "queue_full"
    info = adm.tenants_info()
    assert info["table_size"] <= 3  # 2 rows + "other"
    assert any(r["tenant"] == "other" for r in info["top_shed"])


def test_fair_disabled_is_seed_fifo(monkeypatch):
    """KAKVEDA_TENANT_FAIR=0: the tenant plane vanishes — no quota sheds,
    no tenant table growth, pure class-bound admission (seed behavior)."""
    monkeypatch.setenv("KAKVEDA_TENANT_FAIR", "0")
    adm = _adm()
    adm.try_admit("warn", tenant="app-a")
    adm.try_admit("warn", tenant="app-a")
    adm.try_admit("warn", tenant="app-b")
    adm.try_admit("warn", tenant="app-a")  # over the share cap: admits
    with pytest.raises(OverloadError) as ei:
        adm.try_admit("warn", tenant="app-b")
    assert ei.value.reason == "queue_full"
    info = adm.tenants_info()
    assert not info["fair"] and info["table_size"] == 0
    assert "warn/tenant_quota" not in adm.shed_counts()
    for t in ("app-a", "app-a", "app-b", "app-a"):
        adm.release("warn", tenant=t)


def test_admission_tenant_table_bounded_under_churn(monkeypatch):
    """A key-churn flood (every request a fresh tenant id) must not grow
    the tenant table past its bound — idle rows evict LRU."""
    monkeypatch.setenv("KAKVEDA_TENANT_TABLE", "64")
    adm = _adm(warn=8)
    for i in range(5000):
        t = f"app-{i}"
        adm.try_admit("warn", tenant=t)
        adm.release("warn", tenant=t)
    assert len(adm._tenants) <= 65  # bound + possible "other"
    assert adm.tenants_info()["table_size"] <= 65


# ---------------------------------------------------------------------------
# micro-batcher deficit round-robin
# ---------------------------------------------------------------------------


def _mb(**kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("tenant_key", lambda r: r.split("-")[0])
    return MicroBatcher(lambda reqs: list(reqs), **kw)


def _items(tenant, n):
    # _compose only reads req (index 0) and tenant (index 3).
    return [(f"{tenant}-{i}", SimpleNamespace(), float(i), tenant)
            for i in range(n)]


def test_compose_caps_flooder_share():
    mb = _mb()  # max_batch=4, share 0.5 → per-tenant cap 2
    flood, victim = _items("f", 8), _items("v", 2)
    batch = mb._compose(flood + victim)
    by_tenant = {}
    for item in batch:
        by_tenant.setdefault(item[3], []).append(item[0])
    assert len(by_tenant["f"]) == 2 and len(by_tenant["v"]) == 2
    # Per-tenant FIFO within the batch.
    assert by_tenant["f"] == ["f-0", "f-1"]
    assert by_tenant["v"] == ["v-0", "v-1"]
    # Leftovers carry in original arrival order.
    assert [it[0] for it in mb._carry] == [f"f-{i}" for i in range(2, 8)]


def test_compose_work_conserving_relaxes_cap():
    """Everyone with work capped → the cap relaxes rather than running a
    short batch: spare seats go to whoever has work."""
    mb = _mb()
    batch = mb._compose(_items("f", 8) + _items("v", 1))
    names = [it[0] for it in batch]
    assert len(batch) == 4 and "v-0" in names
    assert [n for n in names if n.startswith("f")] == ["f-0", "f-1", "f-2"]


def test_compose_single_tenant_is_fifo():
    mb = _mb()
    batch = mb._compose(_items("f", 6))
    assert [it[0] for it in batch] == ["f-0", "f-1", "f-2", "f-3"]
    assert [it[0] for it in mb._carry] == ["f-4", "f-5"]


def test_compose_served_table_bounded(monkeypatch):
    monkeypatch.setenv("KAKVEDA_TENANT_TABLE", "8")
    mb = _mb()
    for i in range(100):
        mb._bump_served(f"t{i}", 1)
    assert len(mb._served) <= 8


def test_submit_bound_sheds_flooder_spares_victim():
    """At max_queue depth the shed lands on the tenant that owns the
    backlog; an under-share tenant rides bounded slack up to the hard
    2x bound."""
    mb = _mb(max_queue=4)

    async def go():
        loop = asyncio.get_running_loop()
        for i in range(4):  # flooder owns the whole backlog
            await mb._queue.put((f"f-{i}", loop.create_future(),
                                 time.monotonic(), "f"))
        mb._queued["f"] = 4
        with pytest.raises(OverloadError) as ei:
            await mb.submit("f-next")
        assert ei.value.reason == "tenant_quota" and ei.value.tenant == "f"
        # The victim passes the tenant bound and enqueues into the slack.
        task = asyncio.create_task(mb.submit("v-0"))
        await asyncio.sleep(0.01)
        assert not task.done() and mb._queue.qsize() == 5
        # Hard bound: past 2x max_queue even an under-share tenant sheds.
        for i in range(3):
            await mb._queue.put((f"f-pad{i}", loop.create_future(),
                                 time.monotonic(), "f"))
        with pytest.raises(OverloadError) as ei2:
            await mb.submit("v-1")
        assert ei2.value.reason == "queue_full"
        task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task

    asyncio.run(go())


def test_batcher_fair_disabled_keeps_global_fifo(monkeypatch):
    """KAKVEDA_TENANT_FAIR=0 with a tenant_key still means seed FIFO:
    composition never runs, the submit bound is global."""
    monkeypatch.setenv("KAKVEDA_TENANT_FAIR", "0")
    mb = _mb(max_queue=4)
    assert not mb._fair

    async def go():
        loop = asyncio.get_running_loop()
        for i in range(4):
            await mb._queue.put((f"f-{i}", loop.create_future(),
                                 time.monotonic(), ""))
        with pytest.raises(OverloadError) as ei:
            await mb.submit("v-0")  # victim sheds too: global bound
        assert ei.value.reason == "queue_full" and ei.value.tenant == ""

    asyncio.run(go())


# ---------------------------------------------------------------------------
# serving-engine weighted-fair slot pick
# ---------------------------------------------------------------------------


def _fake_engine(promote=4, fair=True):
    # _pick_waiting_locked touches only this state; avoids building a
    # real engine (and its decode loop) per property-test round.
    return SimpleNamespace(
        _tenant_fair=fair, _promote_rounds=promote, _fair_served={},
        _fair_table_max=512, _fair_picks=0, _fair_promotions=0,
        _waiting=[],
    )


def _witem(tenant):
    return ("req", SimpleNamespace(tenant=tenant, fair_rounds=0))


def _pick(eng):
    from kakveda_tpu.models.serving import ServingEngine

    return ServingEngine._pick_waiting_locked(eng)


def test_deficit_pick_prefers_least_served_tenant():
    eng = _fake_engine()
    eng._fair_served = {"f": 5}
    eng._waiting = [_witem("f"), _witem("f"), _witem("v")]
    item = _pick(eng)
    assert item[-1].tenant == "v"
    # Every item left behind aged by one round.
    assert all(it[-1].fair_rounds == 1 for it in eng._waiting)


def test_starvation_bound_promotes_within_k_rounds():
    """The property the promote knob guarantees: however skewed the
    deficit state and however fast the flooder refills the queue, a
    waiting item is admitted within _promote_rounds picks of reaching
    its tenant's subqueue head."""
    promote = 3
    eng = _fake_engine(promote=promote)
    # Pathological deficit: the victim LOOKS heavy (e.g. after a table
    # eviction re-entry), so the deficit pick alone would starve it.
    eng._fair_served = {"v": 1000}
    victim = _witem("v")
    eng._waiting = [_witem("f") for _ in range(3)] + [victim]
    picks = []
    for _ in range(promote + 1):
        picks.append(_pick(eng)[-1].tenant)
        eng._waiting.append(_witem("f"))  # flooder keeps refilling
        if picks[-1] == "v":
            break
    assert picks[-1] == "v" and len(picks) <= promote + 1
    assert eng._fair_promotions == 1
    assert adm_mod.tenant_promotions().get("serving") == 1


def test_tenant_blind_and_fair_off_are_exact_fifo():
    # fair off short-circuits to pop(0).
    eng = _fake_engine(fair=False)
    eng._waiting = [_witem("a"), _witem("b"), _witem("c")]
    assert _pick(eng)[-1].tenant == "a"
    # fair on, all tenants "": one subqueue → index 0 every time.
    eng2 = _fake_engine()
    eng2._fair_served = {"": 99}
    items = [_witem(""), _witem(""), _witem("")]
    for it in items:
        it[-1].order = id(it)
    eng2._waiting = list(items)
    assert _pick(eng2) is items[0]
    assert _pick(eng2) is items[1]


def test_fair_served_table_bounded():
    eng = _fake_engine()
    eng._fair_table_max = 2
    for i in range(10):
        eng._waiting = [_witem(f"t{i}")]
        _pick(eng)
    assert len(eng._fair_served) <= 2


# ---------------------------------------------------------------------------
# rate-limiter table bound under key churn
# ---------------------------------------------------------------------------


def test_token_bucket_bounded_under_1m_key_churn():
    """1M distinct keys inside one burst window: the bucket table stays
    at its bound (LRU evict on insert), and an evicted key re-enters
    FULL — churn only ever grants tokens, never wrongly denies."""
    tb = TokenBucket(100.0, burst=4.0, max_keys=512)
    now = 0.0
    for i in range(1_000_000):
        now += 1e-6  # far inside every bucket's refill window
        tb.allow(f"k{i}", now=now)
        if i % 250_000 == 0:
            assert len(tb._buckets) <= 512
    assert len(tb._buckets) <= 512
    # An evicted key comes back with a full bucket: admitted.
    ok, retry = tb.allow("k0", now=now)
    assert ok and retry == 0.0


# ---------------------------------------------------------------------------
# noisy-neighbor scenario + SLO gates
# ---------------------------------------------------------------------------


def test_noisy_neighbor_scenario_is_pure_in_seed():
    from kakveda_tpu.traffic.scenarios import make_scenario

    a = make_scenario("noisy_neighbor", seed=3, duration_s=2.0)
    b = make_scenario("noisy_neighbor", seed=3, duration_s=2.0)
    assert a.events == b.events
    c = make_scenario("noisy_neighbor", seed=4, duration_s=2.0)
    assert a.events != c.events
    flood_start = a.notes["flood_start_s"]
    for e in a.events:
        if e["app_id"] == "app-flood":
            assert e["t"] >= flood_start and e["phase"] == "flood"
        else:
            assert e["app_id"].startswith("app-v")
    assert a.slo.flood_app == "app-flood"
    assert a.slo.max_victim_shed_rate is not None


def _rec(app, status, t, phase="flood", lat=10.0):
    return {"klass": "warn", "phase": phase, "app": app, "t": t,
            "status": status, "latency_ms": lat, "late_ms": 0.0}


def _tenant_slo(**kw):
    from kakveda_tpu.traffic.slo import SLO

    kw.setdefault("flood_app", "app-flood")
    kw.setdefault("max_victim_shed_rate", 0.05)
    kw.setdefault("min_flood_shed_share", 0.9)
    kw.setdefault("max_tenant_starvation_s", 1.0)
    kw.setdefault("victim_p95_x_baseline", 3.0)
    return SLO(shed_only=(), **kw)


def test_tenant_gates_pass_when_flooder_absorbs_shed():
    from kakveda_tpu.traffic.replay import ReplayResult
    from kakveda_tpu.traffic.slo import evaluate

    recs = [_rec("app-v0", "ok", t / 10.0, phase="baseline")
            for t in range(10)]
    recs += [_rec("app-v0", "ok", 1.0 + t / 10.0, lat=12.0)
             for t in range(10)]
    recs += [_rec("app-flood", "shed", 1.0 + t / 10.0) for t in range(20)]
    report = evaluate(_tenant_slo(), ReplayResult(records=recs))
    assert report.ok, report.summary()
    gates = {g.gate: g for g in report.gates}
    assert gates["min_flood_shed_share"].observed == 1.0
    assert gates["max_victim_shed_rate"].observed == 0.0


def test_tenant_gates_fail_on_victim_starvation_and_shed():
    from kakveda_tpu.traffic.replay import ReplayResult
    from kakveda_tpu.traffic.slo import evaluate

    recs = [_rec("app-v0", "ok", 0.0, phase="baseline")]
    # 2 s of consecutive victim sheds: starvation AND shed-rate break.
    recs += [_rec("app-v0", "shed", 1.0 + t * 0.2) for t in range(11)]
    recs += [_rec("app-flood", "shed", 1.5)]
    report = evaluate(_tenant_slo(), ReplayResult(records=recs))
    failed = {g.gate for g in report.failures()}
    assert "max_victim_shed_rate" in failed
    assert "max_tenant_starvation_s" in failed
    assert "min_flood_shed_share" in failed  # flooder took 1/12 sheds


def test_tenant_gates_vacuous_without_tenant_accounting():
    from kakveda_tpu.traffic.replay import ReplayResult
    from kakveda_tpu.traffic.slo import evaluate

    recs = [{"klass": "warn", "phase": "flood", "status": "shed",
             "latency_ms": 0.0, "late_ms": 0.0} for _ in range(5)]
    report = evaluate(_tenant_slo(), ReplayResult(records=recs))
    gates = {g.gate: g for g in report.gates}
    for name in ("max_victim_shed_rate", "victim_p95_x_baseline",
                 "max_tenant_starvation_s", "min_flood_shed_share"):
        assert gates[name].ok and gates[name].observed == "no tenant accounting"


def test_replay_result_tenant_accessors():
    from kakveda_tpu.traffic.replay import ReplayResult

    res = ReplayResult(records=[
        _rec("app-v0", "ok", 0.1, lat=5.0),
        _rec("app-v0", "ok", 0.2, lat=7.0),
        _rec("app-flood", "shed", 0.3),
        {"klass": "ingest", "phase": "flood", "app": "app-v0", "t": 0.4,
         "status": "ok", "latency_ms": 3.0, "late_ms": 0.0},
    ])
    counts = res.tenant_counts("warn")
    assert counts["app-v0"] == {"ok": 2}
    assert counts["app-flood"] == {"shed": 1}
    assert res.tenant_latencies_ms("app-v0", klass="warn") == [5.0, 7.0]


# ---------------------------------------------------------------------------
# chaos drill: engine crash mid-flood
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_noisy_neighbor_engine_crash_preserves_victim(monkeypatch):
    """A flooder holds the only slot and a deep waiting queue when the
    loop crashes. The supervisor rebuild re-derives fairness from the
    SURVIVING queue: the victim's request re-admits ahead of the flood
    tail (deficit pick), completes, and nothing hangs."""
    from kakveda_tpu.models.llama import LlamaConfig, init_params
    from kakveda_tpu.models.serving import EngineRetryableError, ServingEngine

    monkeypatch.setenv("KAKVEDA_SERVE_RESTARTS", "2")
    cfg = LlamaConfig(
        vocab_size=264, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, dtype=jax.numpy.float32,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    eng = ServingEngine(params, cfg, batch_slots=1, max_len=64, chunk_steps=4)
    try:
        faults.arm("engine.dispatch:1:1")  # first dispatch kills the loop
        order = []
        flood = []
        for i in range(4):
            f = eng.submit([20 + i], max_new_tokens=4, tenant="app-flood")
            f.add_done_callback(lambda _f, tag=f"f{i}": order.append(tag))
            flood.append(f)
        victim = eng.submit([5, 6, 7], max_new_tokens=4, tenant="app-v0")
        victim.add_done_callback(lambda _f: order.append("v"))
        crashed = 0
        for f in flood:
            try:
                f.result(timeout=120)
            except EngineRetryableError:
                crashed += 1
        vtoks = victim.result(timeout=120)
        assert isinstance(vtoks, list) and len(vtoks) == 4
        assert crashed >= 1  # the in-flight flood request died with the loop
        st = eng.stats()
        assert st["restarts"] == 1 and not st["dead"]
        # Fairness survived the rebuild: the victim beat the flood TAIL —
        # it did not drain behind every surviving flooder request.
        assert order.index("v") < order.index("f3")
        assert st["tenant_fair"]["enabled"]
        assert st["tenant_fair"]["served"].get("app-v0") == 1
    finally:
        eng.close()
