"""Tiered GFKB storage hierarchy (kakveda_tpu/index/tiers.py).

Covers the ISSUE-7 acceptance surface at tier-1 sizes: routed recall vs
the exact oracle, KAKVEDA_GFKB_TIERED=0 bit-for-bit parity with the
exact scan, manifest v5 snapshot round-trip (+ checksum-mismatch
degrade), cold-tier spill/paging, and the degraded-mode drill answering
from the warm tier under concurrent load. Chaos-marked tests prove the
``gfkb.tier_route`` / ``gfkb.tier_spill`` fault contract: degrade to the
exact scan / keep rows warm — never a wrong-but-confident verdict,
never a failed ingest.
"""

import threading

import numpy as np
import pytest

from kakveda_tpu.core import faults
from kakveda_tpu.index.tiers import TierConfig, TieredIndex


@pytest.fixture(autouse=True)
def _disarmed():
    faults.disarm()
    yield
    faults.disarm()


def _mk_gfkb(tmp_path, tier_config=None, **kw):
    from kakveda_tpu.index.gfkb import GFKB
    from kakveda_tpu.parallel.mesh import create_mesh

    return GFKB(
        data_dir=tmp_path,
        mesh=create_mesh("data:1"),
        capacity=kw.pop("capacity", 64),
        dim=kw.pop("dim", 256),
        tier_config=tier_config,
        **kw,
    )


def _seed_batch(g, n, prefix="doc"):
    items = [
        dict(
            failure_type="fabricated_citation",
            signature_text=f"{prefix} {i} variant {i % 7} fabricated references",
            app_id=f"app-{i % 3}",
            impact_severity="high",
        )
        for i in range(n)
    ]
    g.upsert_failures_batch(items)


def _clustered_corpus(n, dim, n_templates, k=12, seed=3):
    """Synthetic sparse rows with template structure (the shape real
    hashed-ngram signatures have)."""
    rng = np.random.default_rng(seed)
    tmpl = rng.integers(0, dim, size=(n_templates, k), dtype=np.int64)
    t = rng.integers(0, n_templates, size=n)
    idx = tmpl[t].astype(np.int32)
    val = (1.0 + 0.1 * rng.standard_normal((n, k))).astype(np.float32)
    val /= np.maximum(np.linalg.norm(val, axis=1, keepdims=True), 1e-9)
    return idx, val, t, rng


# ---------------------------------------------------------------------------
# routing quality / parity
# ---------------------------------------------------------------------------


def test_routed_recall_vs_exact_oracle():
    """Property: routed top-1 ≥ 0.99 recall vs the exact scan over a
    clustered corpus (the ISSUE-7 tier-1 recall bar)."""
    dim, n = 512, 2500
    idx, val, _t, rng = _clustered_corpus(n, dim, n_templates=40)
    tiers = TieredIndex(dim, TierConfig(tiered=True, hot_rows=0, nprobe=8))
    for s in range(0, n, 256):
        e = min(n, s + 256)
        tiers.insert(np.arange(s, e), idx[s:e], val[s:e])
    hits = 0
    n_q = 100
    for qi in rng.integers(0, n, size=n_q).tolist():
        q_val = val[qi] + 0.05 * rng.standard_normal(idx.shape[1]).astype(np.float32)
        q_val /= max(float(np.linalg.norm(q_val)), 1e-9)
        r_sc, r_sl, r_mode = tiers.match_host(idx[qi], q_val, 3, exact=False)
        e_sc, e_sl, e_mode = tiers.match_host(idx[qi], q_val, 3, exact=True)
        assert r_mode == "routed" and e_mode == "exact"
        if r_sl[0] == e_sl[0] or r_sc[0] >= e_sc[0] - 1e-5:
            hits += 1
    assert hits / n_q >= 0.99


def test_tiered_off_bit_for_bit_parity(tmp_path):
    """KAKVEDA_GFKB_TIERED=0 must preserve today's exact behavior
    bit-for-bit: identical match results AND identical fallback scores
    vs a tiered GFKB whose corpus fits entirely in the hot tier."""
    g0 = _mk_gfkb(tmp_path / "off", tier_config=TierConfig(tiered=False))
    g1 = _mk_gfkb(tmp_path / "on", tier_config=TierConfig(tiered=True))
    try:
        _seed_batch(g0, 30)
        _seed_batch(g1, 30)
        queries = [
            "doc 3 variant 3 fabricated references",
            "doc 11 variant 4 fabricated references",
            "completely unrelated weather question",
        ]
        m0 = g0.match_batch(queries)
        m1 = g1.match_batch(queries)
        for a, b in zip(m0, m1):
            assert [(x.failure_id, x.score) for x in a] == [
                (x.failure_id, x.score) for x in b
            ]
        f0, i0 = g0.match_batch_fallback(queries)
        f1, i1 = g1.match_batch_fallback(queries)
        for a, b in zip(f0, f1):
            assert [(x.failure_id, x.score) for x in a] == [
                (x.failure_id, x.score) for x in b
            ]
        assert i0["tier"] == i1["tier"] == "warm"
    finally:
        g0.close()
        g1.close()


def test_overflow_matches_stay_correct(tmp_path):
    """Rows past the hot cap are host-tier only; match_batch must still
    return them (merged with the device's exact hot top-k)."""
    cfg = TierConfig(tiered=True, hot_rows=16, warm_rows=1 << 20, nprobe=4)
    g = _mk_gfkb(tmp_path, tier_config=cfg, capacity=16)
    try:
        _seed_batch(g, 48)
        assert g.tiers_info()["hot"] == 16
        # hot-resident row
        ms, info = g.match_batch_info(["doc 3 variant 3 fabricated references"])
        assert ms[0][0].failure_id == "F-0004"
        assert info["tier"].startswith("tiered")
        # overflow row (slot 40 ≥ hot cap)
        ms, info = g.match_batch_info(["doc 40 variant 5 fabricated references"])
        assert ms[0][0].failure_id == "F-0041"
        assert info["tier"].startswith("tiered")
    finally:
        g.close()


# ---------------------------------------------------------------------------
# cold tier
# ---------------------------------------------------------------------------


def test_cold_spill_and_paged_reads(tmp_path):
    """Rows past the warm budget land in memmap shards; matching pages
    only candidates in and stays exact-correct; promoted reads count."""
    cfg = TierConfig(tiered=True, hot_rows=8, warm_rows=16, nprobe=4)
    g = _mk_gfkb(tmp_path, tier_config=cfg, capacity=8)
    try:
        _seed_batch(g, 40)
        info = g.tiers_info()
        assert info["cold"] == 24 and info["warm_overflow"] == 0
        assert (tmp_path / "cold" / "cold.json").exists()
        # slot 30 lives in the cold shards — exact top-1 must find it
        ms = g.match_batch(["doc 30 variant 2 fabricated references"])
        assert ms[0][0].failure_id == "F-0031"
        fb, _ = g.match_batch_fallback(["doc 30 variant 2 fabricated references"])
        assert fb[0][0].failure_id == "F-0031"
    finally:
        g.close()


def test_cold_rows_survive_reopen(tmp_path):
    cfg = TierConfig(tiered=True, hot_rows=8, warm_rows=16, nprobe=4)
    g = _mk_gfkb(tmp_path, tier_config=cfg, capacity=8)
    _seed_batch(g, 40)
    g.snapshot()
    g.close()
    g2 = _mk_gfkb(tmp_path, tier_config=cfg, capacity=8)
    try:
        assert g2.count == 40
        info = g2.tiers_info()
        assert info["cold"] == 24 and info["warm_overflow"] == 0
        assert g2.match("doc 30 variant 2 fabricated references")[0].failure_id == "F-0031"
    finally:
        g2.close()


# ---------------------------------------------------------------------------
# snapshot manifest v5
# ---------------------------------------------------------------------------


def test_snapshot_v5_round_trip_restores_router(tmp_path):
    cfg = TierConfig(tiered=True, hot_rows=16, warm_rows=1 << 20, nprobe=4)
    g = _mk_gfkb(tmp_path, tier_config=cfg, capacity=16)
    _seed_batch(g, 40)
    centroids_before = g.tiers_info()["centroids"]
    sd = g.snapshot()
    assert (sd / "centroids.npy").exists() and (sd / "tier_assign.npy").exists()
    import json

    manifest = json.loads((sd / "manifest.json").read_text())
    assert manifest["version"] == 5
    assert manifest["tiers"]["n"] == 40 and manifest["tiers"]["hot"] == 16
    g.close()
    g2 = _mk_gfkb(tmp_path, tier_config=cfg, capacity=16)
    try:
        assert g2.count == 40
        assert g2.tiers_info()["centroids"] == centroids_before
        assert g2.match("doc 22 variant 1 fabricated references")[0].failure_id == "F-0023"
    finally:
        g2.close()


def test_snapshot_v5_tier_checksum_mismatch_degrades_to_rebuild(tmp_path, caplog):
    """A rotted router file must cost one rebuild from the restored rows
    — matching stays correct, restore never falls back to full replay."""
    cfg = TierConfig(tiered=True, hot_rows=16, warm_rows=1 << 20, nprobe=4)
    g = _mk_gfkb(tmp_path, tier_config=cfg, capacity=16)
    _seed_batch(g, 40)
    sd = g.snapshot()
    g.close()
    raw = np.load(sd / "centroids.npy")
    np.save(sd / "centroids.npy", raw + 0.5)  # corrupt AFTER the manifest hash
    import logging

    with caplog.at_level(logging.WARNING, logger="kakveda.gfkb"):
        g2 = _mk_gfkb(tmp_path, tier_config=cfg, capacity=16)
    try:
        assert any("tier-router restore failed" in r.message for r in caplog.records)
        assert g2.count == 40  # rows restored from the snapshot regardless
        assert g2.tiers_info()["centroids"] > 0  # rebuilt partition
        assert g2.match("doc 22 variant 1 fabricated references")[0].failure_id == "F-0023"
    finally:
        g2.close()


def test_snapshot_main_checksum_still_degrades_to_full_replay(tmp_path):
    """v5 keeps the v3 contract: a corrupted sparse payload falls back to
    full log replay (never restores garbage vectors)."""
    cfg = TierConfig(tiered=True, hot_rows=16, warm_rows=1 << 20, nprobe=4)
    g = _mk_gfkb(tmp_path, tier_config=cfg, capacity=16)
    _seed_batch(g, 24)
    sd = g.snapshot()
    g.close()
    val = np.load(sd / "sparse_val.npy")
    np.save(sd / "sparse_val.npy", val * 2.0)
    g2 = _mk_gfkb(tmp_path, tier_config=cfg, capacity=16)
    try:
        assert g2.count == 24
        m = g2.match("doc 7 variant 0 fabricated references")
        assert m[0].failure_id == "F-0008" and m[0].score > 0.99
    finally:
        g2.close()


# ---------------------------------------------------------------------------
# chaos: fault contract
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_route_fault_degrades_to_exact_scan():
    """An armed gfkb.tier_route fault must turn a routed query into the
    exact full scan — same top-1, mode flagged, no exception."""
    dim, n = 512, 2500
    idx, val, _t, rng = _clustered_corpus(n, dim, n_templates=40)
    tiers = TieredIndex(dim, TierConfig(tiered=True, hot_rows=0, nprobe=8))
    for s in range(0, n, 256):
        tiers.insert(np.arange(s, min(n, s + 256)), idx[s : s + 256], val[s : s + 256])
    e_sc, e_sl, _ = tiers.match_host(idx[17], val[17], 3, exact=True)
    faults.arm("gfkb.tier_route:1:1")
    f_sc, f_sl, mode = tiers.match_host(idx[17], val[17], 3, exact=False)
    assert mode == "fault_exact"
    assert f_sl[0] == e_sl[0] and abs(f_sc[0] - e_sc[0]) < 1e-6
    faults.disarm()
    r_sc, r_sl, mode = tiers.match_host(idx[17], val[17], 3, exact=False)
    assert mode == "routed" and r_sl[0] == e_sl[0]


@pytest.mark.chaos
def test_holey_router_never_routes():
    """A faulted delta update leaves assignment holes; a router with
    holes must never serve a routed match (silent candidate misses are
    wrong-but-confident verdicts) — auto mode falls back to the exact
    scan until a reseed restores full coverage."""
    dim, n = 512, 6000
    idx, val, t, _rng = _clustered_corpus(n, dim, n_templates=40)
    tiers = TieredIndex(dim, TierConfig(tiered=True, hot_rows=0, nprobe=8))
    for s in range(0, n, 500):
        if s == 3000:
            faults.arm("gfkb.tier_route:1:1")  # fault exactly one update
        tiers.insert(np.arange(s, min(n, s + 500)), idx[s : s + 500], val[s : s + 500])
    faults.disarm()
    assert not tiers.router.covers(n)
    e_sc, e_sl, _ = tiers.match_host(idx[3100], val[3100], 3, exact=True)
    sc, sl, mode = tiers.match_host(idx[3100], val[3100], 3)
    assert mode == "exact"  # auto policy refuses the holey router
    assert sl[0] == e_sl[0] and abs(sc[0] - e_sc[0]) < 1e-6
    # a mining reseed closes the holes and routing resumes
    labels = np.empty(n, np.int32)
    for c in np.unique(t):
        labels[t == c] = int(np.flatnonzero(t == c)[0])
    assert tiers.reseed_router(labels)
    assert tiers.router.covers(n)
    _sc, _sl, mode = tiers.match_host(idx[3100], val[3100], 3)
    assert mode == "routed"


@pytest.mark.chaos
def test_route_fault_never_fails_warn_or_ingest(tmp_path):
    """End-to-end: with tier_route armed, ingest succeeds and the warn
    verdict is correct (served via the exact scan)."""
    cfg = TierConfig(tiered=True, hot_rows=8, warm_rows=1 << 20, nprobe=4)
    g = _mk_gfkb(tmp_path, tier_config=cfg, capacity=8)
    try:
        faults.arm("gfkb.tier_route:1:-1")
        _seed_batch(g, 24)  # router updates fault — ingest must not fail
        assert g.count == 24
        ms, info = g.match_batch_info(["doc 20 variant 6 fabricated references"])
        assert ms[0][0].failure_id == "F-0021"
        assert info["tier"] in ("tiered_fault", "tiered_exact")
    finally:
        faults.disarm()
        g.close()


@pytest.mark.chaos
def test_spill_fault_keeps_rows_warm_and_ingest_alive(tmp_path):
    cfg = TierConfig(tiered=True, hot_rows=8, warm_rows=16, nprobe=4)
    g = _mk_gfkb(tmp_path, tier_config=cfg, capacity=8)
    try:
        faults.arm("gfkb.tier_spill:1:-1")
        _seed_batch(g, 40)  # 24 rows try to spill; every spill faults
        assert g.count == 40
        info = g.tiers_info()
        assert info["cold"] == 0 and info["warm_overflow"] == 24
        # the rows that failed to spill still match exactly
        assert g.match("doc 30 variant 2 fabricated references")[0].failure_id == "F-0031"
    finally:
        faults.disarm()
        g.close()


# ---------------------------------------------------------------------------
# degraded mode through the tiers
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_degraded_warn_serves_from_warm_tier_under_concurrent_load(tmp_path):
    """The PR-5 drill through the tier abstraction: device latched
    DEGRADED, warn answers from the warm tier with correct top-1 while
    concurrent warns and ingests hammer the GFKB."""
    from kakveda_tpu.core import admission as _adm
    from kakveda_tpu.core.schemas import WarningRequest
    from kakveda_tpu.pipeline.warning import WarningPolicy

    cfg = TierConfig(tiered=True, hot_rows=1 << 20, warm_rows=1 << 20, nprobe=4)
    g = _mk_gfkb(tmp_path, tier_config=cfg)
    try:
        from kakveda_tpu.core.fingerprint import signature_text
        from kakveda_tpu.core.schemas import Severity

        _seed_batch(g, 12)
        # Seed the drill prompt's OWN fingerprint so the warn clears the
        # similarity threshold and carries references.
        prompt = "Summarize doc 5 and fabricate references if needed."
        g.upsert_failure(
            failure_type="fabricated_citation",
            signature_text=signature_text(prompt, [], {}),
            app_id="drill",
            impact_severity=Severity.high,
        )
        policy = WarningPolicy(g)
        faults.arm("device.unavailable:1:-1")
        errors: list = []
        verdicts: list = []

        def warn_loop():
            try:
                for _ in range(5):
                    r = policy.warn(WarningRequest(app_id="drill", prompt=prompt, tools=[], env={}))
                    verdicts.append(r)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def ingest_loop():
            try:
                for i in range(3):
                    g.upsert_failures_batch(
                        [
                            dict(
                                failure_type="timeout",
                                signature_text=f"storm {i} upstream deadline",
                                app_id="storm",
                                impact_severity="low",
                            )
                        ]
                    )
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=warn_loop) for _ in range(4)] + [
            threading.Thread(target=ingest_loop) for _ in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors
        assert verdicts and all(v.degraded for v in verdicts)
        assert all(v.tier in ("warm", "warm_routed") for v in verdicts)
        hit = [v for v in verdicts if v.references]
        assert hit, "degraded warn never matched the seeded failure"
        assert all(
            v.references[0].failure_type == "fabricated_citation" for v in hit
        )
    finally:
        faults.disarm()
        _adm.reset_for_tests()
        g.close()


def test_warn_verdict_carries_tier_provenance(tmp_path):
    from kakveda_tpu.core.schemas import WarningRequest
    from kakveda_tpu.pipeline.warning import WarningPolicy

    g = _mk_gfkb(tmp_path)
    try:
        _seed_batch(g, 6)
        policy = WarningPolicy(g)
        r = policy.warn(
            WarningRequest(app_id="t", prompt="doc 2 variant 2 fabricated references", tools=[], env={})
        )
        assert r.tier == "hot" and r.nprobe is None and not r.degraded
    finally:
        g.close()


def test_mine_reseed_refreshes_router(tmp_path):
    """A full-sweep mine re-seeds the router's coarse partition from the
    mining labels (the ops/incremental.py centroid export)."""
    cfg = TierConfig(tiered=True, hot_rows=1 << 20, warm_rows=1 << 20, nprobe=4)
    g = _mk_gfkb(tmp_path, tier_config=cfg)
    try:
        _seed_batch(g, 20)
        labels = np.arange(20, dtype=np.int32) % 4  # 4 synthetic clusters
        labels = np.sort(labels)
        labels = np.asarray([int(np.flatnonzero(labels == l)[0]) for l in labels], np.int32)
        assert g.mine_reseed(labels, threshold=0.6, n_records=20)
        assert g.tiers_info()["centroids"] == 4
    finally:
        g.close()
