"""Causal-tracing tests (core/trace.py, docs/observability.md § Tracing):
wire-format round-trip, deterministic head sampling, ring bounds + orphan
accounting, the never-fail-a-warn chaos contract (trace.record), trace
continuity across router scatter-gather (fleet drill), bus replication →
DLQ → replay continuing the origin trace, histogram exemplars, and
/metrics federation."""

import asyncio
import time
import uuid
from datetime import datetime, timezone

import pytest
from aiohttp.test_utils import TestClient, TestServer

from kakveda_tpu.core import faults
from kakveda_tpu.core import trace as _trace
from kakveda_tpu.core.trace import (
    Tracer,
    assemble_tree,
    format_traceparent,
    parse_traceparent,
    render_trace,
)


def run(coro):
    return asyncio.run(coro)


@pytest.fixture(autouse=True)
def _clean_tracer():
    _trace.get_tracer().reset()
    yield
    _trace.get_tracer().reset()
    faults.disarm()


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------


def test_traceparent_roundtrip():
    tid, sid = uuid.uuid4().hex, uuid.uuid4().hex[:16]
    for sampled in (True, False):
        tp = format_traceparent(tid, sid, sampled)
        assert parse_traceparent(tp) == (tid, sid, sampled)


@pytest.mark.parametrize("garbage", [
    "", "garbage", "00-short-span-01",
    "00-" + "0" * 32 + "-" + "1" * 16 + "-01",   # all-zero trace id
    "00-" + "a" * 32 + "-" + "0" * 16 + "-01",   # all-zero span id
    "00-" + "g" * 32 + "-" + "1" * 16 + "-01",   # non-hex
    "ff-" + "a" * 32 + "-" + "1" * 16 + "-01",   # unknown version
])
def test_traceparent_rejects_garbage(garbage):
    assert parse_traceparent(garbage) is None


def test_start_span_folds_request_id():
    """A fresh x-request-id is 32 lowercase hex — a valid trace id — so
    the request id IS the trace id end to end."""
    tr = Tracer(capacity=16, sample=1.0)
    rid = uuid.uuid4().hex
    span = tr.start_span("service.request", trace_id=rid)
    assert span.trace_id == rid
    span.end("ok")
    # an invalid fold candidate is ignored, never an error
    span = tr.start_span("service.request", trace_id="not-a-trace-id")
    assert span.trace_id != "not-a-trace-id" and len(span.trace_id) == 32
    span.end("ok")


# ---------------------------------------------------------------------------
# sampling
# ---------------------------------------------------------------------------


def test_sampling_deterministic_across_processes():
    """Head sampling is a pure function of (trace_id, rate): every process
    makes the SAME decision for the same trace — a sampled router hop is
    sampled on the replica too, with zero coordination."""
    a, b = Tracer(capacity=16, sample=0.5), Tracer(capacity=16, sample=0.5)
    ids = [uuid.uuid4().hex for _ in range(200)]
    assert [a.sample_decision(t) for t in ids] == [
        b.sample_decision(t) for t in ids
    ]
    # the decision threshold is the id's leading 32 bits
    assert a.sample_decision("00" * 16)
    assert not a.sample_decision("ff" * 16)


def test_sample_zero_still_records_bad_outcomes():
    """KAKVEDA_TRACE_SAMPLE=0: ok spans never touch the ring (hot path
    cost is the sample check), but error/shed/degraded outcomes ALWAYS
    record — the failure platform never drops its own failures."""
    tr = Tracer(capacity=16, sample=0.0)
    tr.start_span("warn").end("ok")
    assert tr.dump() == []
    for outcome in ("error", "shed", "degraded"):
        tr.start_span("warn").end(outcome)
    assert sorted(s["outcome"] for s in tr.dump()) == [
        "degraded", "error", "shed"
    ]
    p = tr.plane()
    assert p["started"] == p["ended"] == 4 and p["orphaned"] == 0


def test_ring_bounded_and_counts_dropped():
    tr = Tracer(capacity=4, sample=1.0)
    for i in range(10):
        tr.start_span(f"s{i}").end("ok")
    spans = tr.dump()
    assert len(spans) == 4
    assert [s["name"] for s in spans] == ["s6", "s7", "s8", "s9"]
    p = tr.plane()
    assert p["recorded"] == 10 and p["dropped"] == 6
    assert p["orphaned"] == 0


def test_assemble_tree_and_render():
    tr = Tracer(capacity=16, sample=1.0)
    with tr.start_span("root") as root:
        with tr.start_span("mid"):
            tr.start_span("leaf").end("ok")
    spans = tr.dump(root.trace_id)
    # duplicates (scatter-assembly) dedupe by span id
    tree = assemble_tree(spans + spans)
    assert len(tree) == 1
    assert tree[0]["name"] == "root"
    assert tree[0]["children"][0]["name"] == "mid"
    assert tree[0]["children"][0]["children"][0]["name"] == "leaf"
    out = render_trace(spans)
    assert out.splitlines()[0].startswith(f"trace {root.trace_id}")
    assert "root" in out and "leaf" in out
    # a missing parent renders as a root instead of vanishing
    orphan_tree = assemble_tree([s for s in spans if s["name"] != "root"])
    assert [t["name"] for t in orphan_tree] == ["mid"]


# ---------------------------------------------------------------------------
# chaos: a failing tracer never fails a warn
# ---------------------------------------------------------------------------


def _platform(tmp_path, name="p"):
    from kakveda_tpu.platform import Platform

    return Platform(data_dir=tmp_path / name, capacity=256, dim=1024)


def _ingest_trace(app_id, prompt):
    from kakveda_tpu.models.runtime import STUB_RESPONSE

    return {
        "trace_id": str(uuid.uuid4()),
        "ts": datetime.now(timezone.utc).isoformat(),
        "app_id": app_id,
        "agent_id": "agent-1",
        "prompt": prompt,
        "response": STUB_RESPONSE,
        "model": "stub",
        "tools": [],
        "env": {"os": "linux"},
    }


@pytest.mark.chaos
def test_trace_record_fault_never_fails_warn(tmp_path):
    """Armed trace.record: every ring append dies — the warn still
    answers 200, spans are counted dropped, nothing orphans. The tracer's
    failure mode is silence, never a failed request."""
    from kakveda_tpu.service.app import make_app

    faults.disarm()
    plat = _platform(tmp_path)
    app = make_app(platform=plat)

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            faults.arm("trace.record:1.0:-1")
            r = await client.post(
                "/warn", json={"app_id": "app-1", "prompt": "hello world"}
            )
            assert r.status == 200
            body = await r.json()
            assert "action" in body
        finally:
            faults.disarm()
            await client.close()

    run(go())
    p = _trace.get_tracer().plane()
    assert p["dropped"] > 0
    assert p["orphaned"] == 0


# ---------------------------------------------------------------------------
# fleet drill: one warn, one assembled cross-process tree
# ---------------------------------------------------------------------------


def test_fleet_drill_assembles_one_tree(tmp_path):
    """One warn through the ownership router over two live replicas:
    GET /trace/{id} on the router returns ONE assembled tree carrying the
    router root, both scatter hops with replica + outcome provenance, the
    replicas' service spans, and the GFKB verdict's tier provenance. The
    trace id is the warn's x-request-id."""
    from kakveda_tpu.fleet.ownership import OwnershipView
    from kakveda_tpu.fleet.router import make_router_app
    from kakveda_tpu.service.app import make_app

    plat_a = _platform(tmp_path, "a")
    plat_b = _platform(tmp_path, "b")

    async def go():
        ca = TestClient(TestServer(make_app(platform=plat_a)))
        cb = TestClient(TestServer(make_app(platform=plat_b)))
        await ca.start_server()
        await cb.start_server()
        urls = {
            "r0": str(ca.make_url("")).rstrip("/"),
            "r1": str(cb.make_url("")).rstrip("/"),
        }
        router = make_router_app(
            urls, probe_interval_s=30.0, eject_fails=5, retries=1,
            timeout_s=10.0, ownership=OwnershipView(urls, replication=1),
        )
        rc = TestClient(TestServer(router))
        await rc.start_server()
        try:
            await ca.post("/ingest", json=_ingest_trace("app-1", "seed row"))
            r = await rc.post(
                "/warn", json={"app_id": "app-1", "prompt": "hello"}
            )
            assert r.status == 200
            tid = r.headers.get("x-request-id")
            assert tid and len(tid) == 32

            r = await rc.get(f"/trace/{tid}")
            assert r.status == 200
            body = await r.json()
            spans = body["spans"]
            assert spans and all(s["trace_id"] == tid for s in spans)
            by_name = {}
            for s in spans:
                by_name.setdefault(s["name"], []).append(s)
            assert "router.request" in by_name
            hops = by_name.get("router.scatter", [])
            assert {h["attrs"]["replica"] for h in hops} == {"r0", "r1"}
            assert all(h["outcome"] == "ok" for h in hops)
            assert len(by_name.get("service.request", [])) == 2
            warns = by_name.get("gfkb.warn", [])
            assert len(warns) == 2 and all("tier" in w["attrs"] for w in warns)
            # one tree: every span hangs off the single router root
            tree = assemble_tree(spans)
            assert len(tree) == 1 and tree[0]["name"] == "router.request"
            assert body["tree"].startswith(f"trace {tid}")
            assert body["sources"]["__router__"] >= 1
            assert set(body["sources"]) == {"__router__", "r0", "r1"}
            assert all(v >= 0 for v in body["sources"].values())
        finally:
            await rc.close()
            await ca.close()
            await cb.close()

    run(go())


# ---------------------------------------------------------------------------
# replication → DLQ → replay continues the origin trace
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_dlq_replay_continues_origin_trace(tmp_path, monkeypatch):
    """The replication envelope carries the ingest's trace context, the
    DLQ record preserves the envelope verbatim, and `dlq replay`'s
    redelivery applies under the SAME trace id — a lost-then-healed row
    is one trace from origin ingest to converged peer."""
    monkeypatch.setenv("KAKVEDA_BUS_RETRIES", "2")
    monkeypatch.setenv("KAKVEDA_BUS_RETRY_BASE", "0.01")
    faults.disarm()
    from kakveda_tpu.events.bus import TOPIC_GFKB_REPLICATE, replay_dlq_file
    from kakveda_tpu.service.app import make_app

    plat_a = _platform(tmp_path, "a")
    plat_b = _platform(tmp_path, "b")
    dlq = tmp_path / "a" / "dlq.jsonl"

    async def go():
        ca = TestClient(TestServer(make_app(platform=plat_a)))
        cb = TestClient(TestServer(make_app(platform=plat_b)))
        await ca.start_server()
        await cb.start_server()
        try:
            plat_a.bus.subscribe(
                TOPIC_GFKB_REPLICATE, str(cb.make_url("/replicate"))
            )
            faults.arm("fleet.replicate_apply:1.0:-1")
            r = await ca.post("/ingest/batch", json={"traces": [
                _ingest_trace(
                    "app-x", f"Cite sources for claim {i} even if unavailable."
                )
                for i in range(3)
            ]})
            assert r.status == 200
            assert (await r.json())["failures"] >= 1
            tid = r.headers.get("x-request-id")
            assert tid and len(tid) == 32
            # delivery retries + dead-lettering run off the response path
            for _ in range(100):
                if dlq.exists() and dlq.read_text().strip():
                    break
                await asyncio.sleep(0.05)
            assert dlq.exists() and dlq.read_text().strip()
        finally:
            await ca.close()
            faults.disarm()
            out = await asyncio.get_running_loop().run_in_executor(
                None, lambda: replay_dlq_file(dlq, timeout=5.0)
            )
            assert out["failed"] == 0 and out["replayed"] >= 1
            await cb.close()
        return tid

    tid = run(go())
    spans = _trace.get_tracer().dump(tid)
    applies = [s for s in spans if s["name"] == "gfkb.replicate_apply"]
    # the armed first delivery errored under the same trace; the replay
    # redelivery applied ok — BOTH continue the origin ingest's trace.
    assert any(s["outcome"] == "error" for s in applies)
    ok = [s for s in applies if s["outcome"] == "ok"]
    assert ok and ok[-1]["attrs"].get("applied", 0) >= 1
    assert any(s["name"] == "gfkb.ingest" for s in spans)


# ---------------------------------------------------------------------------
# histogram exemplars + federation (core/metrics.py)
# ---------------------------------------------------------------------------


def test_histogram_exemplars_render_and_snapshot():
    from kakveda_tpu.core.metrics import MetricsRegistry

    reg = MetricsRegistry()
    h = reg.histogram("t_warn_seconds", "test latency")
    tid_a, tid_b = uuid.uuid4().hex, uuid.uuid4().hex
    h.observe(0.01, exemplar=tid_a)
    h.observe(0.01, exemplar=tid_b)  # last-write-wins per bucket
    h.observe(0.02)  # no exemplar: bucket keeps the old one
    text = reg.render()
    assert f'# {{trace_id="{tid_b}"}} 0.01' in text
    assert tid_a not in text
    snap = reg.snapshot()
    series = snap["t_warn_seconds"]["series"]
    ex = next(iter(series.values()))["exemplar"]
    assert ex["trace_id"] == tid_b and ex["value"] == 0.01


def test_metrics_federation_sums_and_labels():
    """federate_renders: counters and histogram buckets SUM across
    replicas; gauges get a replica label instead (summing occupancies is
    a lie); exemplar suffixes never break the parser."""
    from kakveda_tpu.core.metrics import federate_renders, parse_prometheus_text

    r0 = "\n".join([
        "# HELP w_total warns",
        "# TYPE w_total counter",
        'w_total{app="a"} 3',
        "# HELP occ occupancy",
        "# TYPE occ gauge",
        "occ 0.5",
        "# HELP lat_seconds latency",
        "# TYPE lat_seconds histogram",
        'lat_seconds_bucket{le="0.1"} 2 # {trace_id="abc"} 0.05',
        'lat_seconds_bucket{le="+Inf"} 3',
        "lat_seconds_sum 0.4",
        "lat_seconds_count 3",
    ]) + "\n"
    r1 = "\n".join([
        "# TYPE w_total counter",
        'w_total{app="a"} 4',
        "# TYPE occ gauge",
        "occ 0.9",
        "# TYPE lat_seconds histogram",
        'lat_seconds_bucket{le="0.1"} 5',
        'lat_seconds_bucket{le="+Inf"} 6',
        "lat_seconds_sum 1.0",
        "lat_seconds_count 6",
    ]) + "\n"
    out = federate_renders({"r0": r0, "r1": r1})
    assert 'w_total{app="a"} 7' in out
    assert 'occ{replica="r0"} 0.5' in out
    assert 'occ{replica="r1"} 0.9' in out
    assert 'lat_seconds_bucket{le="0.1"} 7' in out
    assert 'lat_seconds_bucket{le="+Inf"} 9' in out
    assert "lat_seconds_sum 1.4" in out
    assert "lat_seconds_count 9" in out
    # the federated text is itself parseable (round-trip sanity)
    fams = parse_prometheus_text(out)
    assert fams["w_total"]["type"] == "counter"
    assert fams["occ"]["type"] == "gauge"


def test_service_trace_endpoints(tmp_path):
    """GET /trace returns the plane + ring; GET /trace/{id} filters to
    one trace — the per-process collection surface the router's
    scatter-assembler pulls from."""
    from kakveda_tpu.service.app import make_app

    plat = _platform(tmp_path)
    app = make_app(platform=plat)

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post(
                "/warn", json={"app_id": "app-1", "prompt": "hi"}
            )
            assert r.status == 200
            tid = r.headers["x-request-id"]
            body = await (await client.get("/trace")).json()
            # the GET /trace request's own span is still in flight while
            # the handler snapshots the plane — at most that one orphan
            assert body["plane"]["orphaned"] <= 1
            assert any(s["trace_id"] == tid for s in body["spans"])
            body = await (await client.get(f"/trace/{tid}")).json()
            assert body["trace_id"] == tid
            names = {s["name"] for s in body["spans"]}
            assert {"service.request", "gfkb.warn"} <= names
        finally:
            await client.close()

    run(go())


def test_replay_dispatch_spans_tag_records_and_balance():
    """Every replayed dispatch carries a trace tag and its span ends in
    exactly one bucket — the zero-orphan invariant the storm bench row
    certifies — and a failing latency gate emits exemplar trace ids."""
    from kakveda_tpu.traffic.replay import ReplayResult, replay
    from kakveda_tpu.traffic.slo import SLO, evaluate

    events = [
        {"t": 0.0, "klass": "warn", "path": "/warn", "body": {}, "phase": "x"}
        for _ in range(4)
    ]

    async def post(path, body):
        await asyncio.sleep(0.01)
        return 200

    res = run(replay(events, post=post, speed=1000.0, timeout_s=2.0,
                     result=ReplayResult()))
    assert len(res.records) == 4
    assert all(r.get("trace") for r in res.records)
    assert all(r["status"] == "ok" for r in res.records)
    p = _trace.get_tracer().plane()
    assert p["orphaned"] == 0
    # an impossible latency bound fails — with worst-offender exemplars
    report = evaluate(SLO(name="t", warn_p95_ms=0.0001, zero_lost=()), res)
    gate = next(g for g in report.gates if g.gate == "warn_p95_ms")
    assert not gate.ok and gate.exemplars
    assert gate.exemplars[0] in {r["trace"] for r in res.records}
    assert "exemplars" in gate.to_dict()
