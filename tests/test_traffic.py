"""Record-replay traffic harness (kakveda_tpu/traffic/, docs/robustness.md
§ traffic harness): seeded scenario determinism, traffic-log round-trips,
the flight-recorder capture seam, open-loop replay accounting (every
dispatch terminates in exactly one bucket), chaos-timeline application,
SLO gate evaluation, and the satellite mechanisms the harness gates —
Retry-After jitter, gossip pressure-floor decay, note_wait ladder
re-evaluation, DLQ auto-replay on breaker re-close, and router probe
phase stagger. Fault-arming tests and the in-process storm smoke carry
the chaos marker.

Global-state discipline (same as test_overload.py): the admission /
brownout / device-health controllers and the fault registry are
process-global, so every test resets them before AND after."""

import asyncio
import json
import time

import pytest

from kakveda_tpu.core import admission as adm_mod
from kakveda_tpu.core import faults
from kakveda_tpu.core.admission import AdmissionController, BrownoutController
from kakveda_tpu.traffic import (
    ReplayResult,
    SLO,
    SCENARIOS,
    evaluate,
    from_flightrecorder,
    make_scenario,
    read_log,
    replay,
    run_chaos,
    run_scenario,
    write_log,
)


@pytest.fixture(autouse=True)
def _clean_globals():
    faults.disarm()
    adm_mod.reset_for_tests()
    yield
    faults.disarm()
    adm_mod.reset_for_tests()


# ---------------------------------------------------------------------------
# scenarios: pure in (seed, knobs)
# ---------------------------------------------------------------------------


def test_scenario_determinism_every_generator():
    """Same seed → identical arrival schedule, app-key sequence, bodies,
    and chaos timeline — for EVERY registered generator. This is what
    makes a scenario name + seed a reproducible bug report."""
    for name in SCENARIOS:
        a = make_scenario(name, seed=7)
        b = make_scenario(name, seed=7)
        assert a.arrival_schedule() == b.arrival_schedule(), name
        assert a.app_key_sequence() == b.app_key_sequence(), name
        assert a.events == b.events, name
        assert a.chaos == b.chaos, name


def test_scenario_seed_changes_schedule():
    a = make_scenario("hot_key", seed=1)
    b = make_scenario("hot_key", seed=2)
    assert a.arrival_schedule() != b.arrival_schedule()


def test_unknown_scenario_is_typed_error():
    with pytest.raises(ValueError):
        make_scenario("nope")


def test_hot_key_skew_concentrates_on_one_app():
    sc = make_scenario("hot_key", seed=3)
    keys = sc.app_key_sequence()
    hot = keys.count("app-0") / len(keys)
    assert 0.8 < hot < 1.0  # declared 90%, Bernoulli noise allowed


def test_storm_scenario_shape():
    """The composed drill: three phases, a device-loss window that CLOSES
    (disarm is part of the timeline), gossip pressure ticks through
    recovery, and an SLO that never sheds warn/ingest."""
    sc = make_scenario("storm", seed=4, duration_s=12.0, gossip_ttl_s=5.0)
    phases = {e["phase"] for e in sc.events}
    assert {"baseline", "storm", "recovery"} <= phases
    b, s = sc.notes["storm_start_s"], sc.notes["storm_end_s"]
    assert 0.0 < b < s < 12.0
    arms = [a for a in sc.chaos if a["action"] == "faults" and a.get("spec")]
    disarms = [a for a in sc.chaos if a["action"] == "faults" and not a.get("spec")]
    assert arms and disarms, "device-loss window must open AND close"
    assert all("device.unavailable" in a["spec"] for a in arms)
    assert max(a["t"] for a in disarms) > max(a["t"] for a in arms)
    ticks = [a for a in sc.chaos if a["action"] == "fleet_pressure"]
    assert any(a["pressure"] == 0.0 and a["t"] >= s for a in ticks), (
        "recovery needs live zero-pressure gossip ticks (live samples "
        "REPLACE the floor; TTL only covers dead peers)")
    assert "warn" not in sc.slo.shed_only and "ingest" not in sc.slo.shed_only
    assert sc.slo.zero_hung and "warn" in sc.slo.zero_lost
    assert sc.slo.recovery_s == 5.0


# ---------------------------------------------------------------------------
# traffic logs
# ---------------------------------------------------------------------------


def test_log_round_trip_preserves_schedule(tmp_path):
    sc = make_scenario("diurnal", seed=9)
    p = tmp_path / "t.jsonl"
    n = write_log(p, sc.events, meta={"scenario": "diurnal", "seed": 9})
    assert n == len(sc.events)
    meta, events = read_log(p)
    assert meta["scenario"] == "diurnal" and meta["version"] == 1
    assert [e["t"] for e in events] == sc.arrival_schedule()
    assert [e.get("app_id", "") for e in events] == sc.app_key_sequence()


def test_log_read_skips_malformed_lines(tmp_path):
    """Skip-with-warning per line (the bus subscription-replay contract):
    a torn or hand-edited log replays what it can."""
    p = tmp_path / "t.jsonl"
    good = {"t": 0.5, "method": "POST", "path": "/warn", "klass": "warn"}
    p.write_text(
        json.dumps({"kakveda_traffic_log": 1, "meta": {}}) + "\n"
        + "{\"t\": 0.1, \"path\"\n"          # torn mid-object
        + "5\n"                               # valid JSON, not a dict
        + json.dumps({"path": "/warn"}) + "\n"  # no offset
        + json.dumps(good) + "\n"
    )
    _, events = read_log(p)
    assert events == [good]


def test_from_flightrecorder_is_deterministic():
    payload = {"recorders": [{"name": "traffic", "events": [
        {"t": 100.0, "kind": "warn", "app_id": "a", "prompt": "Cite it."},
        {"t": 100.4, "kind": "ingest", "app_id": "b", "n": 3},
        {"t": 101.0, "kind": "warn", "app_id": "a", "prompt": "Again."},
    ]}]}
    ev1 = from_flightrecorder(payload, seed=3)
    ev2 = from_flightrecorder(payload, seed=3)
    assert ev1 == ev2
    assert [e["t"] for e in ev1] == [0.0, 0.4, 1.0]  # rebased to first event
    assert ev1[0]["body"] == {"app_id": "a", "prompt": "Cite it."}  # byte-faithful
    assert len(ev1[1]["body"]["traces"]) == 3  # shape-faithful
    assert from_flightrecorder({"recorders": []}) == []


# ---------------------------------------------------------------------------
# open-loop replay: terminal accounting
# ---------------------------------------------------------------------------


def _ev(t, path, klass, method="POST", phase="baseline"):
    return {"t": t, "method": method, "path": path, "klass": klass,
            "app_id": "app-0", "phase": phase, "body": {}}


def test_replay_buckets_every_outcome():
    """2xx/429/503/other map to ok/shed/degraded/error; a LOCAL event
    without a dispatcher is skipped (and NOT counted as lost); a LOCAL
    event with one records its TTFT. The accounting must balance."""
    statuses = {"/ok": 200, "/shed": 429, "/deg": 503, "/boom": 500}

    async def post(path, body):
        return statuses[path]

    async def gen(event):
        return 0.01  # ttft seconds

    events = [
        _ev(0.0, "/ok", "warn"), _ev(0.0, "/shed", "warn"),
        _ev(0.0, "/deg", "warn"), _ev(0.0, "/boom", "background"),
        _ev(0.0, "/generate", "interactive", method="LOCAL"),
        _ev(0.0, "/nope", "interactive", method="LOCAL"),
    ]
    res = asyncio.run(replay(
        events, post=post, speed=100.0, timeout_s=5.0,
        extra_dispatch={"/generate": gen}))
    counts = res.class_counts()
    assert counts["warn"] == {"ok": 1, "shed": 1, "degraded": 1}
    assert counts["background"] == {"error": 1}
    assert counts["interactive"] == {"ok": 1, "skipped": 1}
    assert res.generated("warn") == 3
    assert res.generated("interactive") == 1  # skipped never entered the system
    assert res.ttft_ms() == [10.0]
    # The gates read this accounting: a warn shed fails shed_only outright,
    # and zero_lost balances because every dispatch landed in a bucket.
    rep = evaluate(SLO(), res)
    by = {g.gate: g for g in rep.gates}
    assert not by["shed_only"].ok and by["shed_only"].observed == {"warn": 1}
    assert by["zero_hung"].ok and by["zero_lost[warn]"].ok


def test_replay_timeout_is_hung_not_lost():
    async def post(path, body):
        await asyncio.sleep(0.5)
        return 200

    res = asyncio.run(replay(
        [_ev(0.0, "/warn", "warn")], post=post, timeout_s=0.05))
    assert res.class_counts()["warn"] == {"hung": 1}
    rep = evaluate(SLO(), res)
    by = {g.gate: g for g in rep.gates}
    assert not by["zero_hung"].ok        # SHED-NEVER-HANG, end to end
    assert by["zero_lost[warn]"].ok      # hung is terminal accounting, not loss


def test_replay_is_open_loop():
    """A slow response must not delay later arrivals (closed-loop clients
    self-throttle and hide the very overload the harness measures)."""
    sends = []

    async def post(path, body):
        sends.append(asyncio.get_running_loop().time())
        await asyncio.sleep(0.3)
        return 200

    events = [_ev(0.0, "/warn", "warn"), _ev(0.05, "/warn", "warn")]
    res = asyncio.run(replay(events, post=post, timeout_s=5.0))
    assert len(sends) == 2
    assert sends[1] - sends[0] < 0.25  # second fired on schedule, not after 0.3s
    assert res.late_p95_ms() < 200.0


@pytest.mark.chaos
def test_replay_dispatch_fault_drops_to_error():
    """The harness's own failure mode (traffic.dispatch, docs/robustness.md
    catalog): an armed dispatch fault loses the request into the error
    bucket — counted, never raised out of the replay."""
    async def post(path, body):
        return 200

    faults.arm("traffic.dispatch:1:-1")
    res = asyncio.run(replay(
        [_ev(0.0, "/warn", "warn"), _ev(0.0, "/warn", "warn")],
        post=post, timeout_s=5.0))
    assert res.class_counts()["warn"] == {"error": 2}
    assert faults.site("traffic.dispatch").fired == 2


# ---------------------------------------------------------------------------
# chaos timelines
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_run_chaos_applies_and_skips():
    """faults entries re-arm the registry (empty spec disarms — disarm IS
    how an outage ends); fleet_pressure feeds the admission controller
    exactly like a gossip sample; actions missing their handle skip with
    a warning instead of failing the run."""
    adm = AdmissionController(enabled=True,
                              brownout=BrownoutController(enabled=False))
    seen = {}

    async def go():
        timeline = [
            {"t": 0.0, "action": "faults", "spec": "device.unavailable:1.0:-1"},
            {"t": 0.01, "action": "fleet_pressure", "pressure": 0.9, "ttl_s": 5.0},
            {"t": 0.02, "action": "kill_replica", "replica": 1},
            {"t": 0.03, "action": "bogus"},
            {"t": 0.3, "action": "faults", "spec": ""},
        ]

        async def probe():
            await asyncio.sleep(0.15)  # well inside the [0.0, 0.3) window
            seen["armed_mid_window"] = faults.site("device.unavailable").armed
        applied, _ = await asyncio.gather(
            run_chaos(timeline, admission=adm), probe())
        return applied

    applied = asyncio.run(go())
    assert seen["armed_mid_window"]
    assert not faults.site("device.unavailable").armed  # window closed
    assert adm.fleet_pressure() == pytest.approx(0.9)
    by_action = {a["action"]: a for a in applied}
    assert by_action["faults"]["applied"]
    assert by_action["fleet_pressure"]["applied"]
    assert not by_action["kill_replica"]["applied"]  # no supervisor handle
    assert not by_action["bogus"]["applied"]


# ---------------------------------------------------------------------------
# SLO gates
# ---------------------------------------------------------------------------


def _fake_result(base_ms, storm_ms, recovery_s=None):
    res = ReplayResult(ladder_recovery_s=recovery_s)
    for ms in base_ms:
        res.records.append({"klass": "warn", "phase": "baseline",
                            "status": "ok", "latency_ms": ms, "late_ms": 0.0})
    for ms in storm_ms:
        res.records.append({"klass": "warn", "phase": "storm",
                            "status": "ok", "latency_ms": ms, "late_ms": 0.0})
    res.generated_counts["warn"] = len(res.records)
    return res


def test_slo_baseline_ratio_gate():
    res = _fake_result([10.0] * 20, [100.0] * 20)
    ok = evaluate(SLO(warn_p95_x_baseline=12.0), res)
    bad = evaluate(SLO(warn_p95_x_baseline=5.0), res)
    g_ok = {g.gate: g for g in ok.gates}["warn_p95_x_baseline"]
    g_bad = {g.gate: g for g in bad.gates}["warn_p95_x_baseline"]
    assert g_ok.ok and g_ok.observed == pytest.approx(10.0)
    assert not g_bad.ok


def test_slo_ratio_gate_vacuous_without_phases():
    """Capture replays have a single phase — the self-normalizing ratio
    gate passes vacuously rather than failing a log that never declared
    a storm."""
    res = ReplayResult()
    res.records.append({"klass": "warn", "phase": "capture", "status": "ok",
                        "latency_ms": 5.0, "late_ms": 0.0})
    res.generated_counts["warn"] = 1
    rep = evaluate(SLO(warn_p95_x_baseline=2.0), res)
    assert {g.gate: g for g in rep.gates}["warn_p95_x_baseline"].ok


def test_slo_recovery_gate():
    never = evaluate(SLO(recovery_s=3.0), _fake_result([1.0], [1.0]))
    slow = evaluate(SLO(recovery_s=3.0), _fake_result([1.0], [1.0], recovery_s=9.0))
    fast = evaluate(SLO(recovery_s=3.0), _fake_result([1.0], [1.0], recovery_s=1.2))
    assert {g.gate: g for g in never.gates}["recovery_s"].observed == "never recovered"
    assert not {g.gate: g for g in slow.gates}["recovery_s"].ok
    assert {g.gate: g for g in fast.gates}["recovery_s"].ok


def test_slo_shed_rate_ceiling():
    res = ReplayResult()
    for status in ("ok", "shed"):
        res.records.append({"klass": "background", "phase": "storm",
                            "status": status, "latency_ms": 1.0, "late_ms": 0.0})
    res.generated_counts["background"] = 2
    at = evaluate(SLO(max_shed_rate={"background": 0.5}, zero_lost=()), res)
    under = evaluate(SLO(max_shed_rate={"background": 0.4}, zero_lost=()), res)
    assert {g.gate: g for g in at.gates}["max_shed_rate[background]"].ok
    assert not {g.gate: g for g in under.gates}["max_shed_rate[background]"].ok


# ---------------------------------------------------------------------------
# satellites: the mechanisms the harness gates
# ---------------------------------------------------------------------------


def test_retry_after_jitter_bounded(monkeypatch):
    """±25% multiplicative spread de-phases the retry wave; jitter=0 keeps
    the honest drain estimate exactly. The typed-429 floor (0.1 s) holds
    either way."""
    monkeypatch.setenv("KAKVEDA_ADMIT_RA_JITTER", "0.25")
    adm = AdmissionController(enabled=True,
                              brownout=BrownoutController(enabled=False))
    samples = [adm.retry_after("warn") for _ in range(200)]
    # No drain rate measured yet → base is the honest 1 s default.
    assert all(0.75 <= s <= 1.25 for s in samples)
    assert max(samples) - min(samples) > 0.05  # actually spread, not constant

    monkeypatch.setenv("KAKVEDA_ADMIT_RA_JITTER", "0")
    adm0 = AdmissionController(enabled=True,
                               brownout=BrownoutController(enabled=False))
    assert {adm0.retry_after("warn") for _ in range(20)} == {1.0}


def test_gossip_pressure_floor_decays_and_ladder_recovers():
    """Satellite drill for the storm's recovery phase, without HTTP: peer
    gossip steps the ladder down; zero-pressure ticks (live samples
    REPLACE the floor) bring it back to `normal` — and an expired TTL
    stops a silent peer from pinning the ladder. Every transition runs
    through _set_brownout_state (the single writer is what the gauge
    vector + transition counter ride on)."""
    adm = AdmissionController(
        enabled=True,
        brownout=BrownoutController(enabled=True, enter=0.85, exit=0.5,
                                    dwell_s=0.0))
    for _ in range(4):
        adm.note_fleet_pressure(0.95, ttl_s=5.0)
    assert adm.brownout.state == "shed_interactive"
    assert adm.brownout.class_shed("interactive")
    assert not adm.brownout.class_shed("warn")  # ladder never sheds warn

    t0 = time.monotonic()
    for _ in range(8):
        adm.note_fleet_pressure(0.0, ttl_s=5.0)
        if adm.brownout.state == "normal":
            break
    assert adm.brownout.state == "normal"
    assert time.monotonic() - t0 < 5.0  # inside the gossip TTL, by a mile
    assert adm.fleet_pressure() == 0.0

    # TTL path: a peer that goes SILENT (no zero tick) expires off the floor.
    adm.note_fleet_pressure(0.95, ttl_s=0.1)
    assert adm.fleet_pressure() == pytest.approx(0.95)
    time.sleep(0.15)
    assert adm.fleet_pressure() == 0.0


def test_note_wait_reevaluates_ladder():
    """warn traffic flows through the micro-batcher's bounded queue, never
    try_admit/release — note_wait (one call per batch drain) must feed the
    ladder, or a warn-only recovery tail produces ZERO pressure samples
    and the ladder freezes at its storm step."""
    adm = AdmissionController(
        enabled=True,
        brownout=BrownoutController(enabled=True, enter=0.85, exit=0.5,
                                    dwell_s=0.0))
    adm.note_fleet_pressure(0.95, ttl_s=0.1)
    adm.note_fleet_pressure(0.95, ttl_s=0.1)
    assert adm.brownout.state != "normal"
    time.sleep(0.15)  # floor expired; only warn drains tick from here on
    for _ in range(8):
        adm.note_wait("warn", 0.001)
        if adm.brownout.state == "normal":
            break
    assert adm.brownout.state == "normal"


def test_router_probe_phase_stagger():
    """Per-replica probe phases: deterministic (blake2b of the replica id —
    the ring's derivation discipline), inside [0, interval), and actually
    spread so N replicas don't see N simultaneous probes per interval."""
    from kakveda_tpu.fleet.router import Router

    backends = {f"replica-{i}": f"http://127.0.0.1:{9000 + i}" for i in range(6)}
    r1 = Router(backends, probe_interval_s=2.0)
    r2 = Router(backends, probe_interval_s=2.0)
    phases = {rid: r1.probe_phase(rid) for rid in backends}
    assert phases == {rid: r2.probe_phase(rid) for rid in backends}
    assert all(0.0 <= p < 2.0 for p in phases.values())
    assert len(set(phases.values())) > 1


@pytest.mark.chaos
def test_dlq_auto_replay_on_breaker_reclose(tmp_path, monkeypatch):
    """Full arc: delivery fails → DLQ + breaker open → endpoint heals →
    half-open probe succeeds → breaker RE-closes → the bus schedules one
    auto-replay (KAKVEDA_DLQ_AUTO_S) that drains the dead-letter queue
    without an operator. Safe because replay is idempotent for
    subscribers by contract."""
    monkeypatch.setenv("KAKVEDA_BUS_RETRIES", "1")
    monkeypatch.setenv("KAKVEDA_BUS_BREAKER_THRESHOLD", "1")
    monkeypatch.setenv("KAKVEDA_BUS_BREAKER_COOLDOWN", "0")
    monkeypatch.setenv("KAKVEDA_DLQ_AUTO_S", "0.05")
    from aiohttp import web
    from aiohttp.test_utils import TestServer

    from kakveda_tpu.events.bus import EventBus

    received = []

    async def hook(request):
        received.append((await request.json()).get("n"))
        return web.json_response({"ok": True})

    async def go():
        app = web.Application()
        app.router.add_post("/hook", hook)
        server = TestServer(app)
        await server.start_server()
        try:
            url = str(server.make_url("/hook"))
            dlq = tmp_path / "dlq.jsonl"
            bus = EventBus(dlq_path=dlq)
            bus.subscribe("t", url)

            faults.arm("bus.deliver:1:-1")
            assert await bus.publish("t", {"n": 1}) == 0
            assert bus.breaker_states()[url] == "open"
            assert len(dlq.read_text().splitlines()) == 1

            # Endpoint heals: cooldown 0 → this delivery is the half-open
            # probe; success re-closes the breaker and arms the timer.
            faults.disarm()
            assert await bus.publish("t", {"n": 2}) == 1
            assert bus.breaker_states()[url] == "closed"

            # The timer thread replays via sync HTTP while this loop is
            # parked in sleep — poll for the drain.
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if dlq.read_text() == "" and 1 in received:
                    break
                await asyncio.sleep(0.05)
            assert dlq.read_text() == ""
            assert received == [2, 1]  # live event first, then the replay
            assert bus._m_dlq_auto.labels(result="scheduled").value >= 1
            assert bus._m_dlq_auto.labels(result="replayed").value >= 1
        finally:
            await server.close()

    asyncio.run(go())


def test_bus_close_cancels_pending_dlq_auto_timer(tmp_path, monkeypatch):
    """The DLQ auto-replay timer's close path: a bus shut down while a
    replay is pending cancels the timer (no delivery fires against a
    torn-down platform), close() is idempotent, and a closed bus never
    arms another timer."""
    monkeypatch.setenv("KAKVEDA_BUS_RETRIES", "1")
    monkeypatch.setenv("KAKVEDA_BUS_BREAKER_THRESHOLD", "1")
    monkeypatch.setenv("KAKVEDA_BUS_BREAKER_COOLDOWN", "0")
    monkeypatch.setenv("KAKVEDA_DLQ_AUTO_S", "0.2")
    from aiohttp import web
    from aiohttp.test_utils import TestServer

    from kakveda_tpu.events.bus import EventBus

    received = []

    async def hook(request):
        received.append((await request.json()).get("n"))
        return web.json_response({"ok": True})

    async def go():
        app = web.Application()
        app.router.add_post("/hook", hook)
        server = TestServer(app)
        await server.start_server()
        try:
            url = str(server.make_url("/hook"))
            dlq = tmp_path / "dlq.jsonl"
            bus = EventBus(dlq_path=dlq)
            bus.subscribe("t", url)

            faults.arm("bus.deliver:1:-1")
            assert await bus.publish("t", {"n": 1}) == 0
            faults.disarm()
            assert await bus.publish("t", {"n": 2}) == 1  # re-close arms timer
            assert bus._dlq_auto_timer is not None

            bus.close()
            assert bus._dlq_auto_timer is None
            bus.close()  # idempotent

            await asyncio.sleep(0.4)  # past the would-have-fired deadline
            assert len(dlq.read_text().splitlines()) == 1  # never replayed
            assert received == [2]

            # A closed bus never arms another timer.
            faults.arm("bus.deliver:1:-1")
            await bus.publish("t", {"n": 3})
            faults.disarm()
            await bus.publish("t", {"n": 4})
            assert bus._dlq_auto_timer is None
        finally:
            await server.close()

    asyncio.run(go())


# ---------------------------------------------------------------------------
# capture seam + storm smoke (through the real HTTP stack)
# ---------------------------------------------------------------------------


def _mk_service(tmp_path, adm, dim=256):
    from kakveda_tpu.platform import Platform
    from kakveda_tpu.service.app import make_app

    plat = Platform(data_dir=tmp_path / "data", capacity=1 << 10, dim=dim)
    return make_app(platform=plat, admission=adm)


def test_capture_roundtrip_over_http(tmp_path):
    """The whole record path: real warn/ingest arrivals land in the
    traffic flight-recorder ring → GET /flightrecorder converts to a log
    → the log replays against the same service with nothing lost. Same
    dump + same seed → identical log (capture→replay determinism)."""
    from aiohttp.test_utils import TestClient, TestServer

    from kakveda_tpu.traffic.scenarios import synth_traces

    adm = AdmissionController(enabled=True,
                              brownout=BrownoutController(enabled=False))
    app = _mk_service(tmp_path, adm)

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            for i in range(3):
                r = await client.post("/warn", json={
                    "app_id": f"app-{i % 2}",
                    "prompt": f"Cite sources for claim {i}.",
                })
                assert r.status == 200
            r = await client.post("/ingest/batch", json={
                "traces": synth_traces(0, "app-9", 2)})
            assert r.status in (200, 202)

            r = await client.get("/flightrecorder")
            payload = await r.json()
            ev1 = from_flightrecorder(payload, seed=3)
            assert from_flightrecorder(payload, seed=3) == ev1
            assert [e["klass"] for e in ev1] == ["warn"] * 3 + ["ingest"]
            assert ev1[0]["t"] == 0.0

            p = tmp_path / "cap.jsonl"
            write_log(p, ev1, meta={"source": "test"})
            _, events = read_log(p)
            assert [e["t"] for e in events] == [e["t"] for e in ev1]
            assert ([e.get("app_id") for e in events]
                    == [e.get("app_id") for e in ev1])

            async def post(path, body):
                resp = await client.post(path, json=body)
                await resp.read()
                return resp.status

            res = await replay(events, post=post, speed=100.0, timeout_s=30.0)
            counts = res.class_counts()
            assert counts["warn"] == {"ok": 3}
            assert counts["ingest"] == {"ok": 1}
        finally:
            await client.close()

    asyncio.run(go())


@pytest.mark.chaos
def test_storm_smoke_slo_gated(tmp_path):
    """The acceptance drill, sized for tier-1: seeded storm (hot-key warn
    + background mine flood + device-loss window + gossiped pressure)
    through the real HTTP stack, every SLO gate asserted — zero hung,
    zero lost warns, sheds confined to sheddable classes, bounded warn
    degradation, ladder back at `normal` within the gossip TTL."""
    from aiohttp.test_utils import TestClient, TestServer

    sc = make_scenario("storm", seed=5, duration_s=8.0, gossip_ttl_s=3.0)
    brown = BrownoutController(enabled=True, enter=0.85, exit=0.5, dwell_s=0.25)
    # warn sized for DEGRADED throughput: the device-loss window serves
    # warn from the host tiers, and the ladder never sheds warn — the
    # class limit must clear the storm's arrival rate at warm-tier speed.
    adm = AdmissionController(
        limits={"warn": 64, "ingest": 2, "interactive": 8, "background": 1},
        enabled=True, brownout=brown)
    app = _mk_service(tmp_path, adm)

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            async def post(path, body):
                resp = await client.post(path, json=body)
                await resp.read()
                return resp.status

            return await run_scenario(
                sc, post=post, speed=1.5, timeout_s=15.0, admission=adm,
                recovery_horizon_s=20.0)
        finally:
            await client.close()

    res = asyncio.run(go())
    report = evaluate(sc.slo, res)
    assert report.ok, report.summary()
    assert res.generated("warn") > 50  # the drill actually drove traffic
    counts = res.class_counts()
    assert counts.get("warn", {}).get("shed", 0) == 0
    assert counts.get("warn", {}).get("hung", 0) == 0
    assert res.ladder_recovery_s is not None and res.ladder_recovery_s <= 3.0
