"""Tier-1 guard: scripts/verify_static.sh — the one-shot pre-commit
static gate (invariant lint + knob parity + ledger smoke) — passes on
the committed tree. CI and the pre-commit habit share one entry point;
this test is what keeps the script from rotting."""

import os
import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]


def test_verify_static_green():
    env = dict(os.environ)
    # the script runs its own interpreter; keep the axon site dir so jax
    # backend registration survives (CLAUDE.md PYTHONPATH gotcha)
    env.setdefault("PYTHONPATH", os.pathsep.join(
        p for p in (env.get("PYTHONPATH", ""), str(ROOT)) if p
    ))
    r = subprocess.run(
        ["bash", str(ROOT / "scripts" / "verify_static.sh")],
        capture_output=True, text=True, timeout=300, cwd=str(ROOT), env=env,
    )
    assert r.returncode == 0, f"\n{r.stdout}\n{r.stderr}"
    assert "ledger smoke: ok" in r.stdout
    assert "verify_static: all stages green" in r.stdout


def test_verify_static_changed_mode_accepts_flag():
    r = subprocess.run(
        ["bash", str(ROOT / "scripts" / "verify_static.sh"), "--changed"],
        capture_output=True, text=True, timeout=300, cwd=str(ROOT),
    )
    assert r.returncode == 0, f"\n{r.stdout}\n{r.stderr}"


def test_script_uses_python_executable_on_path():
    """The script must not hardcode an interpreter path — it runs under
    whatever `python` the caller's env resolves (tier-1, probe loop,
    operator shell)."""
    src = (ROOT / "scripts" / "verify_static.sh").read_text()
    assert "set -euo pipefail" in src
    assert sys.executable not in src
